"""Sparse NDArrays — row_sparse and CSR storage.

Reference: include/mxnet/ndarray.h:61-66 (storage types),
src/operator/tensor/ (cast_storage, sparse dot in dot-inl.h,
sparse_retain), python/mxnet/ndarray/sparse.py.

TPU-native design (SURVEY §7 hard part (a)): the TPU has no sparse
memory ops, so sparse arrays keep their compressed parts
(data/indices/indptr) as dense jax arrays and compute lowers to
gather/scatter/segment-sum — which XLA maps well — rather than
pointer-chasing kernels. Dense materialization is lazy and cached.
row_sparse exists for its real use-case: touching only the rows a batch
referenced (embedding grads, lazy optimizer updates, kvstore
row_sparse_pull)."""

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray, array as _nd_array, zeros as _nd_zeros

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix",
           "row_sparse_array", "BaseSparseNDArray", "retain",
           "cast_storage", "dot", "add", "zeros",
           "rand_sparse_ndarray"]


class BaseSparseNDArray(NDArray):
    """Common behavior: lazy dense materialization through ._data."""

    __slots__ = ("_dense_cache",)

    def __init__(self, shape, ctx=None, stype="default"):
        self._dense_cache = None
        self._shape = shape
        super(BaseSparseNDArray, self).__init__(None, ctx, stype=stype)

    # NDArray stores the payload in _data; for sparse arrays that slot
    # is a lazily-built dense view of the compressed parts.
    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._to_dense_jax()
        return self._dense_cache

    @_data.setter
    def _data(self, value):
        self._dense_cache = value

    @property
    def shape(self):
        return tuple(self._shape)

    @property
    def dtype(self):
        return self.data.dtype

    def _to_dense_jax(self):
        raise NotImplementedError

    def todense(self):
        return NDArray(self._data, self._ctx)

    def asnumpy(self):
        return np.asarray(self._data)

    def tostype(self, stype):
        return cast_storage(self, stype)

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(map(str, self.shape)), self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (ndarray.h kCSRStorage)."""

    __slots__ = ("_sp_data", "_sp_indices", "_sp_indptr", "_shape",
                 "_row_ids")

    def __init__(self, data, indices, indptr, shape, ctx=None):
        self._sp_data = jnp.asarray(data)
        self._sp_indices = jnp.asarray(indices, jnp.int32)
        self._sp_indptr = jnp.asarray(indptr, jnp.int32)
        # static per-nnz row ids let dot lower to one segment_sum
        counts = np.diff(np.asarray(indptr))
        self._row_ids = jnp.asarray(
            np.repeat(np.arange(shape[0]), counts), jnp.int32)
        super(CSRNDArray, self).__init__(shape, ctx, stype="csr")

    @property
    def data(self):
        return NDArray(self._sp_data, self._ctx)

    @property
    def indices(self):
        return NDArray(self._sp_indices, self._ctx)

    @property
    def indptr(self):
        return NDArray(self._sp_indptr, self._ctx)

    def _to_dense_jax(self):
        dense = jnp.zeros(self.shape, self._sp_data.dtype)
        return dense.at[self._row_ids, self._sp_indices].set(self._sp_data)

    def __getitem__(self, i):
        return self.todense()[i]


class RowSparseNDArray(BaseSparseNDArray):
    """Subset of rows + their indices (ndarray.h kRowSparseStorage)."""

    __slots__ = ("_sp_data", "_sp_indices", "_shape")

    def __init__(self, data, indices, shape, ctx=None):
        self._sp_data = jnp.asarray(data)
        self._sp_indices = jnp.asarray(indices, jnp.int32)
        super(RowSparseNDArray, self).__init__(shape, ctx,
                                               stype="row_sparse")

    @property
    def data(self):
        return NDArray(self._sp_data, self._ctx)

    @property
    def indices(self):
        return NDArray(self._sp_indices, self._ctx)

    def _to_dense_jax(self):
        dense = jnp.zeros(self.shape, self._sp_data.dtype)
        if self._sp_indices.size == 0:
            return dense
        return dense.at[self._sp_indices].set(self._sp_data)

    def __getitem__(self, i):
        return self.todense()[i]


# ------------------------------------------------------------ factories --
def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """csr_matrix((data, indices, indptr), shape=...), an (M, N) shape
    tuple (empty matrix), or a dense array/NDArray (reference sparse.py
    csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 2 and \
            all(isinstance(i, int) for i in arg1):
        return zeros("csr", arg1, ctx, dtype)
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        assert shape is not None, "shape is required"
        data = np.asarray(data, dtype=dtype or np.float32)
        return CSRNDArray(data, indices, indptr, shape, ctx)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(
        arg1, dtype=dtype or np.float32)
    assert dense.ndim == 2, "csr_matrix requires 2D input"
    indptr = [0]
    indices = []
    data = []
    for row in dense:
        nz = np.nonzero(row)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(np.asarray(data, dense.dtype), indices, indptr,
                      dense.shape, ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """row_sparse_array((data, indices), shape=...), a shape tuple
    (empty array), or a dense array."""
    if isinstance(arg1, tuple) and all(isinstance(i, int) for i in arg1):
        return zeros("row_sparse", arg1, ctx, dtype)
    if isinstance(arg1, tuple) and len(arg1) == 2 and \
            not np.isscalar(arg1[0]):
        data, indices = arg1
        assert shape is not None, "shape is required"
        data = np.asarray(data, dtype=dtype or np.float32)
        return RowSparseNDArray(data, indices, shape, ctx)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(
        arg1, dtype=dtype or np.float32)
    nz_rows = np.nonzero(np.any(dense.reshape(dense.shape[0], -1) != 0,
                                axis=1))[0]
    return RowSparseNDArray(dense[nz_rows], nz_rows, dense.shape, ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = dtype or np.float32
    if stype == "csr":
        return CSRNDArray(np.zeros((0,), dtype), np.zeros((0,), np.int32),
                          np.zeros((shape[0] + 1,), np.int32), shape, ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(np.zeros((0,) + tuple(shape[1:]), dtype),
                                np.zeros((0,), np.int64), shape, ctx)
    if stype == "default":
        return _nd_zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError("unknown storage type %s" % stype)


def rand_sparse_ndarray(shape, stype, density=0.1, dtype=None):
    """Random sparse array + its dense equivalent (test helper used by
    mx.test_utils.rand_ndarray)."""
    dense = np.zeros(shape, dtype=dtype or np.float32)
    mask = np.random.rand(*shape) < density
    dense[mask] = np.random.randn(int(mask.sum()))
    if stype == "csr":
        arr = csr_matrix(dense, ctx=None, dtype=dtype)
    else:
        arr = row_sparse_array(dense, ctx=None, dtype=dtype)
    return arr, dense


# ----------------------------------------------------------------- ops --
def cast_storage(arr, stype):
    """src/operator/tensor/cast_storage.cc."""
    if stype == arr.stype:
        return arr
    if stype == "default":
        return arr.todense() if isinstance(arr, BaseSparseNDArray) else arr
    if stype == "csr":
        return csr_matrix(arr.asnumpy())
    if stype == "row_sparse":
        return row_sparse_array(arr.asnumpy())
    raise MXNetError("unknown storage type %s" % stype)


def retain(arr, indices):
    """sparse_retain: keep only the given rows of a RowSparseNDArray."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    keep = np.asarray(indices.asnumpy() if isinstance(indices, NDArray)
                      else indices, np.int64)
    have = np.asarray(arr._sp_indices)
    pos = {r: i for i, r in enumerate(have.tolist())}
    sel = [r for r in keep.tolist() if r in pos]
    rows = np.asarray([pos[r] for r in sel], np.int64) if sel else np.zeros((0,), np.int64)
    data = jnp.asarray(np.asarray(arr._sp_data)[rows]) if len(rows) else \
        jnp.zeros((0,) + arr.shape[1:], arr._sp_data.dtype)
    return RowSparseNDArray(data, np.asarray(sel, np.int64), arr.shape,
                            arr._ctx)


import functools as _functools


@_functools.partial(jax.jit, static_argnames=("num_rows",))
def _csr_dot(sp_data, sp_indices, row_ids, dense, num_rows):
    gathered = sp_data[:, None] * dense[sp_indices]
    return jax.ops.segment_sum(gathered, row_ids, num_segments=num_rows)


@_functools.partial(jax.jit, static_argnames=("num_cols",))
def _csr_t_dot(sp_data, sp_indices, row_ids, dense, num_cols):
    contrib = sp_data[:, None] * dense[row_ids]
    out = jnp.zeros((num_cols, dense.shape[1]), contrib.dtype)
    return out.at[sp_indices].add(contrib)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (dot-inl.h): csr x dense and csr.T x dense lower
    to segment-sum / scatter-add, jit-compiled (cached per nnz/shape)."""
    from . import ndarray as nd
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs,
                                                      BaseSparseNDArray):
        dense = rhs._data
        if transpose_a:
            # out[c] += data[k] * dense[row_ids[k]] scattered to indices
            return NDArray(_csr_t_dot(lhs._sp_data, lhs._sp_indices,
                                      lhs._row_ids, dense,
                                      num_cols=lhs.shape[1]), lhs._ctx)
        return NDArray(_csr_dot(lhs._sp_data, lhs._sp_indices,
                                lhs._row_ids, dense,
                                num_rows=lhs.shape[0]), lhs._ctx)
    if isinstance(lhs, BaseSparseNDArray):
        lhs = lhs.todense()
    if isinstance(rhs, BaseSparseNDArray):
        rhs = rhs.todense()
    return nd.dot(lhs, rhs, transpose_a=transpose_a,
                  transpose_b=transpose_b)


def add(lhs, rhs):
    """Sparse-aware add; rsp+rsp stays row_sparse, anything else falls
    back to dense."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs,
                                                        RowSparseNDArray):
        assert lhs.shape == rhs.shape
        l_idx = np.asarray(lhs._sp_indices)
        r_idx = np.asarray(rhs._sp_indices)
        idx = np.union1d(l_idx, r_idx)
        dense = np.zeros((len(idx),) + lhs.shape[1:],
                         np.asarray(lhs._sp_data).dtype
                         if lhs._sp_data.size else np.float32)
        # vectorized scatter-add of both operands' rows
        np.add.at(dense, np.searchsorted(idx, l_idx),
                  np.asarray(lhs._sp_data))
        np.add.at(dense, np.searchsorted(idx, r_idx),
                  np.asarray(rhs._sp_data))
        return RowSparseNDArray(dense, idx, lhs.shape, lhs._ctx)
    from . import ndarray as nd
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return nd.add(l, r)
