"""Legacy executor-manager helpers (reference:
python/mxnet/executor_manager.py).

The reference's DataParallelExecutorGroup (one executor per GPU with
hand-split batches) dissolves on TPU: data parallelism is a sharded
global array over the mesh (mxnet_tpu.parallel / gluon.utils
split_and_load). What survives here are the workload-splitting helpers
old user code imports."""

from .base import MXNetError

__all__ = ["split_input_slice", "check_arguments"]


def split_input_slice(batch_size, work_load_list):
    """Split batch_size into per-device slices proportional to
    work_load_list (reference _split_input_slice)."""
    total = sum(work_load_list)
    if total <= 0:
        raise MXNetError("Invalid workload")
    slices = []
    start = 0
    for i, load in enumerate(work_load_list):
        end = batch_size if i == len(work_load_list) - 1 else \
            min(batch_size, start + int(round(batch_size * load / total)))
        if end <= start:
            raise MXNetError(
                "Too many slices. Some splits are empty.")
        slices.append(slice(start, end))
        start = end
    return slices


_split_input_slice = split_input_slice


def check_arguments(symbol):
    """Reject symbols with duplicate argument/aux names (reference
    _check_arguments)."""
    names = symbol.list_arguments()
    dup = {n for n in names if names.count(n) > 1}
    if dup:
        raise MXNetError(
            "Find duplicated argument name %s" % sorted(dup))
    aux = symbol.list_auxiliary_states()
    dupa = {n for n in aux if aux.count(n) > 1}
    if dupa:
        raise MXNetError(
            "Find duplicated auxiliary param name %s" % sorted(dupa))


_check_arguments = check_arguments
