"""Weight initializers.

Reference: python/mxnet/initializer.py:57-715 (Initializer base with
registry + InitDesc attr-routing, Xavier/MSRAPrelu/Bilinear/LSTMBias/
FusedRNN and friends).

TPU note: initialization happens host-side in numpy then lands on device
in one transfer — there is no per-element device loop to hide, and doing
it in numpy keeps jit caches clean of init-only computations.
"""

import json
import math
import re

import numpy as np

from . import ndarray as nd
from . import random as _random

__all__ = ["InitDesc", "Initializer", "Zero", "One", "Constant", "Uniform",
           "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Mixed", "Load", "register", "create"]

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _INIT_REGISTRY[name.lower()](**kwargs)


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (initializer.py:34)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer(object):
    """Base init with name-pattern dispatch (initializer.py:57-188)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func or (lambda x: None)
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        if desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "")
        if init:
            try:
                nm, kw = json.loads(init)
            except (json.JSONDecodeError, ValueError):
                nm, kw = init, {}  # plain registry name, e.g. "zeros"
            create(nm, **kw)._init_weight(desc, arr)
        else:
            # routing by name suffix (initializer.py:125-160)
            if desc.endswith("weight"):
                self._init_weight(desc, arr)
            elif desc.endswith("bias"):
                self._init_bias(desc, arr)
            elif desc.endswith("gamma"):
                self._init_gamma(desc, arr)
            elif desc.endswith("beta"):
                self._init_beta(desc, arr)
            elif desc.endswith("min"):
                self._init_zero(desc, arr)
            elif desc.endswith("max"):
                self._init_one(desc, arr)
            elif desc.endswith("weight_quantize"):
                self._init_quantized_weight(desc, arr)
            else:
                self._init_default(desc, arr)

    def _set(self, arr, value):
        arr[:] = nd.array(np.asarray(value, dtype=np.float32)
                          .astype(np.dtype("float32")))._data.astype(arr.dtype) \
            if not np.isscalar(value) else value

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_quantized_weight(self, _, arr):
        arr[:] = nd.array(np.random.randint(-127, 127, size=arr.shape),
                          dtype="int8")._data

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s." % name)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    """U(-scale, scale) (initializer.py:441)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape) \
            .astype(np.float32)


@register
class Normal(Initializer):
    """N(0, sigma) (initializer.py:467)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape).astype(np.float32)


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init (initializer.py:493)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * res).reshape(arr.shape).astype(np.float32)


@register
class Xavier(Initializer):
    """Xavier/Glorot (initializer.py:540): factor_type in/out/avg,
    rnd_type uniform/gaussian."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.
        if len(shape) < 2:
            raise ValueError("Xavier initializer cannot be applied to vector "
                             "{0}. It requires at least 2D.".format(name))
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, arr.shape).astype(np.float32)
        elif self.rnd_type == "gaussian":
            arr[:] = np.random.normal(0, scale, arr.shape).astype(np.float32)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """He init adjusted for PReLU slope (initializer.py:611)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2. / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (initializer.py:634)."""

    def _init_weight(self, _, arr):
        weight = np.zeros(np.prod(arr.shape), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.)
        c = (2 * f - 1 - f % 2) / (2. * f)
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (initializer.py:660): bias layout
    [input, forget, cell, output] each of hidden size."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype="float32")
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = b


class Mixed(object):
    """Pattern-routed initializer list (initializer.py:225)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError("Parameter name %s did not match any pattern. "
                         'Consider adding a ".*" pattern at the end.' % name)


@register
class Load(object):
    """Init from a dict of saved params (initializer.py:257)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = nd.load(param)
        self.param = {}
        for name, arr in param.items():
            if name.startswith("arg:") or name.startswith("aux:"):
                self.param[name[4:]] = arr
            else:
                self.param[name] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if arr.shape != self.param[name].shape:
                raise ValueError("Parameter %s cannot be initialized from "
                                 "loading. Shape mismatch, target %s vs loaded "
                                 "%s" % (name, str(arr.shape),
                                         self.param[name].shape))
            arr[:] = self.param[name]._data
        else:
            if self.default_init is None:
                raise ValueError("Cannot Initialize parameter %s. Not found in "
                                 "loaded param and no default Initializer is "
                                 "provided." % name)
            self.default_init(name, arr)


# FusedRNN initializer needs the rnn cell param layout; provided in
# rnn.rnn_cell once cells exist. Placeholder registered name for parity.
@register
class FusedRNN(Initializer):
    """Init for fused RNN packed params (initializer.py:344). The packed
    vector is de-concatenated into per-gate weights, each initialized with
    `init`, biases with forget_bias where applicable."""

    def __init__(self, init, num_hidden, num_layers, mode, bidirectional=False,
                 forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = _INIT_REGISTRY[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        # The packed vector layout (weights then biases, ops/nn.py RNN op)
        # carries no per-chunk shape metadata here; weights get uniform
        # init, biases (the trailing 2*dirs*layers*gates*h entries) get 0
        # with the forget-gate quarter at forget_bias for LSTM.
        ngates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[self._mode]
        h = self._num_hidden
        dirs = 2 if self._bidirectional else 1
        total = int(np.prod(arr.shape))
        nbias = 2 * dirs * self._num_layers * ngates * h
        flat = np.random.uniform(-0.07, 0.07, (total,)).astype("float32")
        bias = np.zeros((nbias,), dtype="float32")
        if self._mode == "lstm":
            per = ngates * h
            for b in range(nbias // per):
                bias[b * per + h:b * per + 2 * h] = self._forget_bias
        flat[total - nbias:] = bias
        arr[:] = flat.reshape(arr.shape)


# Name aliases matching the reference registry (python/mxnet/initializer.py
# registers Zero under 'zeros', One under 'ones', MSRAPrelu under 'msra').
_INIT_REGISTRY["zeros"] = Zero
_INIT_REGISTRY["ones"] = One
_INIT_REGISTRY["msra"] = MSRAPrelu
