"""KVStore — the data-parallel communication layer.

Reference: include/mxnet/kvstore.h:59-411 (Init/Push/Pull/PullRowSparse,
set_updater, update_on_kvstore), factory src/kvstore/kvstore.cc:40-75
('local'/'device'/'nccl'/'dist_*' types), python/mxnet/kvstore.py:97-635.

TPU-native design: the reference has three comm stacks (CPU tree-reduce in
comm.h, NCCL kvstore_nccl.h, ps-lite parameter server kvstore_dist.h).
On TPU all three collapse into XLA collectives over the ICI/DCN mesh:

- 'local' / 'device'  — single-process aggregation. Values pushed from N
  replicas are summed with one fused jnp add-tree (XLA emits an efficient
  reduction; for sharded arrays it becomes an all-reduce over ICI).
- 'dist_tpu_sync' ('dist_sync'/'dist_device_sync' aliases) — values that
  live sharded over a jax.sharding.Mesh are reduced with psum-style
  collectives compiled by XLA; across hosts the same program runs SPMD so
  Push/Pull semantics match the reference's synchronous PS mode without a
  server role. Async PS ('dist_async') is unsupported by design —
  documented divergence (SURVEY §2.3).

`update_on_kvstore` semantics (kvstore_dist_server.h ApplyUpdates) are
preserved: when an optimizer is set, Push applies the update to the stored
weight and Pull returns weights; otherwise Push aggregates gradients and
Pull returns the aggregate.

Gradient fusion (this layer's DDP-class optimization, parallel/fusion.py):
``pushpull_fused`` packs many keys into fixed-byte buckets
(MXNET_KVSTORE_BUCKET_BYTES, default 25 MB) and runs ONE collective per
bucket dtype-lane instead of one per key — the reference's comm.h key
grouping + bigarray bound, expressed as fused XLA dispatches. Behind
MXNET_KVSTORE_SHARD_UPDATE=1 each bucket lowers to reduce-scatter ->
sharded optimizer update -> all-gather, cutting replicated optimizer
FLOPs and master/optimizer state bytes per replica by (N-1)/N
("Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training", PAPERS.md). ``dispatch_stats`` counts collective dispatches so
benchmark/allreduce_overlap_bench.py can report per-key vs bucketed
dispatch counts and busbw.
"""

import functools
import pickle

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import ndarray as nd
from . import optimizer as opt
from .gradient_compression import GradientCompression
from .ndarray import NDArray
from .observability import chaos as _chaos
from .observability import core as _obs
from .observability import integrity as _integrity
from .observability import membudget as _membudget
from .observability import watchdog as _wd

__all__ = ["KVStore", "KVStoreLocal", "KVStoreTPUSync", "create"]


def _key_str(key):
    return str(key)


@jax.jit
def _sum_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


class KVStore(object):
    """Base single-process store (python/mxnet/kvstore.py:97)."""

    def __init__(self):
        self._store = {}          # key -> NDArray (aggregated value / weight)
        self._updater = None
        self._optimizer = None
        self._gc = GradientCompression()
        self._residuals = {}      # (key, worker_idx) -> flat residual array
        self._barrier_count = 0
        self._fusion_plans = {}   # plan signature -> list[Bucket]
        self._shard_slots = {}    # (bucket_idx, lane_dtype) -> ShardSlot
        self._pending_shard_state = None
        self.dispatch_stats = {"collectives": 0, "keys": 0, "buckets": 0,
                               "shard_updates": 0}

    def reset_dispatch_stats(self):
        for k in self.dispatch_stats:
            self.dispatch_stats[k] = 0

    def _count(self, name, delta=1):
        """dispatch_stats is the always-on cheap view; the same
        increments feed the observability counter registry when
        MXNET_OBS is on, so traces/aggregates/prometheus see the
        collective traffic without a second bookkeeping path."""
        self.dispatch_stats[name] += delta
        if _obs.enabled():
            _obs.counter("kvstore." + name).add(delta)

    # ------------------------------------------------------------- init --
    def init(self, key, value):
        """Initialize key(s) once (kvstore.py:141)."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = v[0].copy() if isinstance(v, (list, tuple)) else v.copy()

    def _normalize(self, key, value):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        if single:
            values = [value]
        else:
            values = list(value)
        keys = [_key_str(k) for k in keys]
        return keys, values

    # -------------------------------------------------------- push/pull --
    def _maybe_compress(self, k, datas):
        """Run each worker's value through quantize->dequantize with its
        error-feedback residual (reference: worker-side Quantize, server-
        side Dequantize around the wire; gradient_compression.h)."""
        if not self._gc.active:
            return datas
        outs = []
        for i, d in enumerate(datas):
            rkey = (k, i)
            residual = self._residuals.get(rkey)
            if residual is None:
                residual = self._gc.init_residual(d.shape)
            recon, residual = self._gc.compress_decompress(d, residual)
            self._residuals[rkey] = residual
            outs.append(recon.astype(d.dtype))
        return outs

    def _aggregate(self, k, datas):
        """Sum per-worker arrays on device (comm.h CommCPU/CommDevice
        reduce). Subclasses lower this to mesh collectives."""
        return _sum_n(*datas) if len(datas) > 1 else datas[0]

    def push(self, key, value, priority=0):
        """Aggregate values (kvstore.py:234). priority is accepted for API
        parity; XLA schedules collectives so ordering hints are moot."""
        keys, values = self._normalize(key, value)
        with _obs.span("kvstore.push", cat="collective", keys=len(keys)), \
                _wd.watch("kvstore.push", keys=len(keys)):
            if _chaos.enabled():
                # chaos site: delay/hang here models a rank stalling in
                # its collective dispatch (armed under the watchdog)
                _chaos.fire("kvstore.push", keys=len(keys))
            for k, v in zip(keys, values):
                vlist = v if isinstance(v, (list, tuple)) else [v]
                datas = self._maybe_compress(k, [x._data for x in vlist])
                self._count("collectives")
                self._count("keys")
                if _obs.enabled():
                    _obs.counter("kvstore.bytes_reduced", "bytes").add(
                        vlist[0].size
                        * np.dtype(vlist[0].dtype).itemsize)
                agg = NDArray(self._aggregate(k, datas), vlist[0]._ctx)
                if self._updater is not None:
                    if k not in self._store:
                        raise ValueError(
                            "Please initialize key %s first" % k)
                    # ApplyUpdates path (kvstore_dist_server.h:346)
                    self._updater(int(k) if k.isdigit() else k, agg,
                                  self._store[k])
                else:
                    self._store[k] = agg

    @staticmethod
    def _pull_into(src, dst):
        """Copy the stored value into a destination NDArray, KEEPING the
        destination's device placement: the store may hold values
        replicated over the whole mesh (dist_tpu_sync), and handing that
        sharding to an eager caller whose other arrays live on one
        device would poison every later jit with a device-set mix."""
        data = jnp.asarray(src._data, dtype=dst.dtype)
        dsh = getattr(dst._data, "sharding", None)
        ssh = getattr(data, "sharding", None)
        if dsh is not None and ssh is not None:
            try:
                if ssh.device_set != dsh.device_set:
                    data = jax.device_put(data, dsh)
            except AttributeError:
                pass
        dst._data = data

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast current value into out (kvstore.py:318)."""
        assert out is not None
        keys, outs = self._normalize(key, out)
        with _obs.span("kvstore.pull", cat="collective", keys=len(keys)), \
                _wd.watch("kvstore.pull", keys=len(keys)):
            if _chaos.enabled():
                _chaos.fire("kvstore.pull", keys=len(keys))
            for k, o in zip(keys, outs):
                if k not in self._store:
                    raise ValueError("Please initialize key %s first" % k)
                olist = o if isinstance(o, (list, tuple)) else [o]
                src = self._store[k]
                for dst in olist:
                    self._pull_into(src, dst)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    # ------------------------------------------------- fused push/pull --
    def supports_shard_update(self):
        """Whether this store can lower buckets to reduce-scatter ->
        sharded update -> all-gather (needs a device mesh)."""
        return False

    def _shard_devices(self):
        return None

    def pushpull_fused(self, key, value, out=None, bucket_bytes=None):
        """Bucketed fused push+pull: group the keys, IN THE GIVEN
        (priority) ORDER, into fixed-byte buckets and run one
        aggregation per bucket dtype-lane instead of one per key
        (reference comm.h key grouping / MXNET_KVSTORE_BIGARRAY_BOUND;
        torch-DDP bucket semantics).

        value: per key, an NDArray or list of per-worker NDArrays (the
        worker count must agree across keys). Semantics per bucket:

        * no updater — the lane aggregate is stored and (when ``out``
          is given) written back to the outs, exactly like push+pull.
        * updater set — the aggregate updates the stored weight. With
          MXNET_KVSTORE_SHARD_UPDATE=1 (and a supported optimizer) the
          whole bucket runs as reduce-scatter -> 1/N sharded optimizer
          update -> all-gather; otherwise the updater applies per key
          on the replicated aggregate (bit-exact with per-key push).
          ``out`` then receives the updated weights.
        """
        from .parallel import fusion
        keys, values = self._normalize(key, value)
        vlists = [list(v) if isinstance(v, (list, tuple)) else [v]
                  for v in values]
        nw = len(vlists[0])
        if any(len(v) != nw for v in vlists):
            raise ValueError(
                "pushpull_fused requires the same worker count on "
                "every key")
        outs = None
        if out is not None:
            okeys, outs_n = self._normalize(key, out)
            assert okeys == keys
            outs = {k: (o if isinstance(o, (list, tuple)) else [o])
                    for k, o in zip(okeys, outs_n)}
        datas = {k: self._maybe_compress(k, [x._data for x in vl])
                 for k, vl in zip(keys, vlists)}
        ctxs = {k: vl[0]._ctx for k, vl in zip(keys, vlists)}
        entries = [(k, tuple(vl[0].shape), str(np.dtype(vl[0].dtype)))
                   for k, vl in zip(keys, vlists)]
        sig = fusion.plan_signature(entries, bucket_bytes)
        plan = self._fusion_plans.get(sig)
        if plan is None:
            plan = self._fusion_plans[sig] = fusion.plan_buckets(
                entries, bucket_bytes)
        flat_opt = None
        if self._updater is not None and fusion.shard_update_enabled() \
                and self.supports_shard_update():
            flat_opt = fusion.FlatOptimizer.supports(self._optimizer)
        self._count("keys", len(keys))
        with _obs.span("kvstore.pushpull_fused", cat="collective",
                       keys=len(keys), buckets=len(plan), workers=nw):
            for bucket in plan:
                self._count("buckets")
                for lane in bucket.lanes:
                    self._fused_lane(bucket, lane, datas, ctxs, outs,
                                     flat_opt, nw)

    def _fused_lane(self, bucket, lane, datas, ctxs, outs, flat_opt, nw):
        from .parallel import fusion
        slot = None
        if flat_opt is not None:
            slot = self._shard_slot(bucket, lane, flat_opt)
        lane_span = _obs.span(
            "kvstore.bucket", cat="collective", bucket=bucket.index,
            lane=lane.dtype, bytes=lane.nbytes, keys=len(lane.segments),
            shard=slot is not None, workers=nw)
        lane_span.start()
        # hang watchdog armed per collective dispatch: the post-mortem
        # names the bucket/dtype lane that never completed
        lane_wd = _wd.watch(
            "kvstore.pushpull_fused", bucket=bucket.index,
            lane=lane.dtype, bytes=lane.nbytes, keys=len(lane.segments),
            shard=slot is not None).start()
        try:
            if _chaos.enabled():
                # per-lane chaos site, armed under the lane watchdog:
                # the post-mortem for an injected hang names this
                # bucket/dtype lane
                _chaos.fire("kvstore.pushpull_fused",
                            bucket=bucket.index, lane=lane.dtype)
            if _obs.enabled():
                _obs.counter("kvstore.bucket_bytes",
                             "bytes").add(lane.nbytes)
            pad = slot.l_pad if slot is not None else None
            per_worker = [
                fusion.pack_lane(lane,
                                 {s.key: datas[s.key][w]
                                  for s in lane.segments}, pad_to=pad)
                for w in range(nw)]
            if _chaos.enabled():
                # SDC in the packed bucket buffer that is about to
                # feed (and poison) the collective — the integrity
                # replay audit's prey
                per_worker = [
                    _chaos.bitflip_array(
                        "kvstore.bucket.pack", f, bucket=bucket.index,
                        lane=lane.dtype, worker=w)
                    for w, f in enumerate(per_worker)]
            if _integrity.enabled():
                # record the flats the collective consumes + a clean
                # re-pack from the (immutable) source arrays; the
                # step-boundary replay audit compares the digests
                _integrity.note_lane(
                    bucket.index, lane.dtype, per_worker,
                    lambda lane=lane, pad=pad: [
                        fusion.pack_lane(lane,
                                         {s.key: datas[s.key][w]
                                          for s in lane.segments},
                                         pad_to=pad)
                        for w in range(nw)])
            if slot is not None:
                # reduce-scatter -> sharded update -> all-gather (2
                # fused collective dispatches however many keys ride
                # the bucket)
                for seg in lane.segments:
                    self._optimizer._update_count(
                        self._opt_index(seg.key))
                flat_new = slot.step(per_worker)
                self._count("collectives", 2)
                self._count("shard_updates")
                news = fusion.unpack_lane(flat_new, lane)
                for seg in lane.segments:
                    self._store[seg.key]._data = news[seg.key]
            else:
                self._count("collectives")
                agg_flat = self._aggregate("__fused_b%d" % bucket.index,
                                           per_worker)
                news = fusion.unpack_lane(agg_flat, lane)
                for seg in lane.segments:
                    k = seg.key
                    agg = NDArray(news[k], ctxs[k])
                    if self._updater is not None:
                        if k not in self._store:
                            raise ValueError(
                                "Please initialize key %s first" % k)
                        self._updater(self._opt_index(k), agg,
                                      self._store[k])
                    else:
                        self._store[k] = agg
            if outs is not None:
                for seg in lane.segments:
                    src = self._store[seg.key]
                    for dst in outs[seg.key]:
                        self._pull_into(src, dst)
        finally:
            # an injected (or real) dispatch failure must not leave the
            # lane's watchdog token armed — that would fire a spurious
            # hang post-mortem for a collective that already raised
            lane_wd.stop()
            lane_span.stop()

    @staticmethod
    def _opt_index(k):
        return int(k) if k.isdigit() else k

    def _shard_slot(self, bucket, lane, flat_opt):
        from .parallel import fusion
        sid = (bucket.index, lane.dtype)
        slot = self._shard_slots.get(sid)
        if slot is None:
            for seg in lane.segments:
                if seg.key not in self._store:
                    raise ValueError(
                        "Please initialize key %s first" % seg.key)
            weights = {seg.key: self._store[seg.key]._data
                       for seg in lane.segments}
            slot = fusion.ShardSlot(
                lane, self._shard_devices(), weights, flat_opt,
                t0=getattr(self._optimizer, "begin_num_update", 0))
            pending = self._pending_shard_state
            if pending and str(sid) in pending:
                slot.set_state(pending.pop(str(sid)))
            self._shard_slots[sid] = slot
        return slot

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (kvstore.py:377).

        Two destination modes (SURVEY §7 sparse divergence):

        * ``out`` is a RowSparseNDArray — COMPACT pull: only the
          gathered rows + their indices are stored, so memory and
          traffic stay proportional to touched rows even on a
          multi-million-row embedding table (the reference's
          row_sparse benefit, preserved).
        * ``out`` is dense (e.g. an executor arg slot, Module.prepare)
          — the rows scatter into a full-width zeroed buffer, which
          materializes the whole table: fine for model-sized tables,
          O(vocab) HBM for giant ones. Pass a RowSparseNDArray out to
          stay compact at that scale.
        """
        assert out is not None and row_ids is not None
        from .sparse import RowSparseNDArray
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        if len(rids) == 1 and len(outs) > 1:
            rids = rids * len(outs)
        for k, o, r in zip(keys, outs, rids):
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            for dst in olist:
                idx = r._data.astype("int32").reshape(-1)
                if isinstance(dst, RowSparseNDArray):
                    # row_sparse invariant: indices unique and sorted
                    # (minibatch row_ids routinely repeat; duplicates
                    # would double-count in sparse add/retain). The
                    # dense path below needs no dedup — .at[].set is
                    # last-write-wins
                    idx = jnp.unique(idx)
                    dst._sp_data = src._data[idx]
                    dst._sp_indices = idx
                    dst._dense_cache = None
                else:
                    dst._data = jnp.zeros_like(dst._data).at[idx].set(
                        src._data[idx])
                    dst._stype = "row_sparse"

    # -------------------------------------------------------- optimizer --
    def set_optimizer(self, optimizer):
        """Run the optimizer inside the store (kvstore.py:446) — the
        update_on_kvstore path. The reference pickles the optimizer to PS
        servers; here the store is in-process so we attach an Updater."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression (kvstore.py:512 /
        gradient_compression.h): each pushed worker value is quantized to
        ±threshold/0 2-bit codes with an error-feedback residual, then
        reconstructed before aggregation — exactly the reference's
        worker-Quantize / server-Dequantize wire semantics."""
        params = dict(compression_params)
        self._gc = GradientCompression(
            type=params.get("type", "none"),
            threshold=float(params.get("threshold", 0.5)))
        self._residuals.clear()

    @property
    def gradient_compression(self):
        return self._gc

    # ------------------------------------------------------------ misc --
    @property
    def type(self):
        return "local"

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        self._barrier_count += 1

    def send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        payload = self._updater.get_states(dump_optimizer)
        if self._shard_slots:
            # sharded-update state (flat master weight + optimizer
            # state per bucket lane) rides alongside the updater's
            # per-key states so a shard-update run round-trips
            payload = pickle.dumps(
                {"__fused_shard_v1__": True, "updater": payload,
                 "slots": {str(sid): slot.get_state()
                           for sid, slot in self._shard_slots.items()}})
        with open(fname, "wb") as fout:
            fout.write(payload)

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            raw = fin.read()
        try:
            loaded = pickle.loads(raw)
        except Exception:
            loaded = None
        if isinstance(loaded, dict) and loaded.get("__fused_shard_v1__"):
            self._updater.set_states(loaded["updater"])
            slots = dict(loaded["slots"])
            for sid, slot in self._shard_slots.items():
                snap = slots.pop(str(sid), None)
                if snap is not None:
                    slot.set_state(snap)
            # slots not materialized yet (fresh store): hydrate lazily
            # when the first fused push creates them
            self._pending_shard_state = slots or None
        else:
            self._updater.set_states(raw)


class KVStoreLocal(KVStore):
    """'local' — aggregation on the default device (comm.h CommCPU)."""
    @property
    def type(self):
        return "local"


class KVStoreDevice(KVStore):
    """'device' — aggregation stays on accelerator (comm.h CommDevice).
    Identical execution here: XLA places the reduction on device."""
    @property
    def type(self):
        return "device"


@functools.lru_cache(maxsize=256)
def _allreduce_jit(mesh_devices, shape, dtype):
    """Compiled worker-axis reduction: input one shard per device along a
    'worker' axis, output replicated — XLA lowers this to an all-reduce
    over ICI/DCN (the dist_tpu_sync wire path). Cached per
    (devices, shape, dtype) so repeated pushes reuse the executable."""
    mesh = Mesh(np.asarray(mesh_devices), ("worker",))
    in_s = NamedSharding(mesh, P("worker"))
    out_s = NamedSharding(mesh, P())
    return jax.jit(lambda g: jnp.sum(g, axis=0),
                   in_shardings=in_s, out_shardings=out_s)


class KVStoreTPUSync(KVStore):
    """'dist_tpu_sync' — synchronous data parallelism over a device mesh.

    Push takes per-worker values (list of NDArrays). They are laid out as
    one shard per mesh device along a leading 'worker' axis and reduced
    by a compiled XLA collective (all-reduce over ICI within a slice, DCN
    across slices); the aggregate lands replicated on every device, so
    Pull is communication-free. This replaces the reference's ps-lite
    push/pull (kvstore_dist.h:209,215) + server ApplyUpdates with one
    SPMD program — sync semantics identical, no server role.
    rank/num_workers reflect the jax process (multi-host SPMD).
    """

    def __init__(self, mesh=None):
        super().__init__()
        from .parallel import current_mesh
        self._mesh = mesh or current_mesh()
        self._flat_devices = tuple(self._mesh.devices.reshape(-1))
        self._replicated = NamedSharding(
            Mesh(np.asarray(self._flat_devices), ("worker",)), P())
        self._per_proc = None
        self._proc_sharding = None
        if _obs.enabled() and jax.process_count() > 1:
            # barrier-handshake clock calibration at store creation:
            # every rank exits the same tiny collective within its
            # completion skew, so the anchors mark one global instant —
            # merge_traces aligns the per-rank trace timelines with it
            from .observability import dist as _obs_dist
            _obs_dist.record_clock_anchor(barrier_fn=self._clock_barrier)

    def _clock_barrier(self):
        self._cross_process_allreduce([jnp.ones((1,), jnp.float32)])

    def init(self, key, value):
        """Stored values live replicated over the whole mesh so the
        update_on_kvstore path (replicated grad x stored weight) is one
        SPMD computation with no device mismatch. In a multi-process job
        the store stays process-local: every rank holds an identical
        copy and applies identical (all-reduced) updates — the same
        invariant, without non-addressable global arrays in the eager
        path."""
        super().init(key, value)
        if jax.process_count() > 1:
            return
        keys, _ = self._normalize(key, value)
        for k in keys:
            v = self._store[k]
            v._data = jax.device_put(v._data, self._replicated)

    def _aggregate(self, k, datas):
        n = len(datas)
        devs = self._flat_devices
        if jax.process_count() > 1:
            return self._cross_process_allreduce(datas)
        if n <= 1 or n != len(devs):
            # worker count doesn't match the mesh (e.g. a single pushed
            # value, or fewer replicas than devices): the fused on-device
            # sum tree is still exact — no collective layout to exploit;
            # replicate the result so downstream update/pull stay SPMD
            return jax.device_put(super()._aggregate(k, datas),
                                  self._replicated)
        shape = tuple(datas[0].shape)
        mesh = Mesh(np.asarray(devs), ("worker",))
        shards = [jax.device_put(jnp.asarray(d)[None], dev)
                  for d, dev in zip(datas, devs)]
        global_arr = jax.make_array_from_single_device_arrays(
            (n,) + shape, NamedSharding(mesh, P("worker")), shards)
        reduce_fn = _allreduce_jit(devs, (n,) + shape,
                                   str(datas[0].dtype))
        if _obs.enabled():
            # per-operator attribution: the bucketed-reduce program is
            # a jit boundary like CachedOp/Executor — register it so
            # --obs-ops / tools/obs_ops.py break its HBM traffic down
            # next to the model step's (one dict probe when warm)
            from .observability import attribution as _obs_attr
            if _obs_attr.ops_enabled():
                _obs_attr.register_program(
                    "KVStore.allreduce",
                    "%s[%s]x%d" % (datas[0].dtype, ",".join(
                        str(d) for d in shape), n),
                    reduce_fn, (global_arr,))
        if _membudget.enabled():
            _membudget.preflight(
                "KVStore.allreduce", reduce_fn, (global_arr,),
                signature="%s[%s]x%d" % (datas[0].dtype, ",".join(
                    str(d) for d in shape), n))
        try:
            return reduce_fn(global_arr)
        except Exception as exc:
            _membudget.note_oom("KVStore.allreduce", exc)
            raise

    def _cross_process_allreduce(self, datas):
        """Multi-host push: sum the local contributions, then one global
        all-reduce with one shard per process (the dist_sync wire path —
        every rank calls in collectively, mirroring the reference's
        NumWorkers()-merge in kvstore_dist_server.h:346). Returns the
        summed value as a process-local array so the updater/pull path
        stays eager-friendly."""
        local = jnp.asarray(_sum_n(*datas) if len(datas) > 1 else datas[0])
        per_proc, sharding = self._process_topology()
        # this is THE blocking rendezvous of a multi-host step: a rank
        # that never dispatches leaves every peer stuck fetching the
        # reduced shard, so the hang watchdog brackets dispatch + fetch
        with _wd.watch("kvstore.allreduce", nprocs=len(per_proc),
                       shape=str(tuple(local.shape)),
                       dtype=str(local.dtype)):
            if _chaos.enabled():
                # chaos site: a delay/hang HERE is one rank arriving
                # late at the multi-host rendezvous — the exact failure
                # the watchdog + straggler detector exist for
                _chaos.fire("kvstore.allreduce", nprocs=len(per_proc))
            mine = jax.device_put(local[None],
                                  per_proc[jax.process_index()])
            global_arr = jax.make_array_from_single_device_arrays(
                (len(per_proc),) + tuple(local.shape), sharding, [mine])
            reduce_fn = _allreduce_jit(
                per_proc, (len(per_proc),) + tuple(local.shape),
                str(local.dtype))
            out = reduce_fn(global_arr)
            # fully-replicated: the local shard IS the global sum
            out = out.addressable_data(0)
            if _wd.enabled():
                # completion must land inside the armed window; the
                # unwatched path keeps XLA's async dispatch
                jax.block_until_ready(out)
        return out

    def _process_topology(self):
        """One representative device per process + the worker sharding —
        static for the job, computed once (pushes run per key per step)."""
        if self._per_proc is None:
            per_proc = tuple(
                next(d for d in jax.devices() if d.process_index == p)
                for p in range(jax.process_count()))
            mesh = Mesh(np.asarray(per_proc), ("worker",))
            self._per_proc = per_proc
            self._proc_sharding = NamedSharding(mesh, P("worker"))
        return self._per_proc, self._proc_sharding

    def supports_shard_update(self):
        # the sharded update is an SPMD program over the mesh; the
        # multi-process eager path keeps per-rank replicas instead
        return jax.process_count() == 1 and len(self._flat_devices) > 1

    def _shard_devices(self):
        return self._flat_devices

    @property
    def type(self):
        return "dist_tpu_sync"

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    @property
    def num_dead_node(self):
        return 0

    def barrier(self):
        # XLA collectives are themselves barriers; an explicit sync point:
        for v in self._store.values():
            v.wait_to_read()
        super().barrier()


def create(name="local"):
    """mx.kvstore.create (kvstore.py:635 / src/kvstore/kvstore.cc:40)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu"):
        return KVStoreLocal()
    if name in ("device", "local_allreduce_device", "nccl"):
        return KVStoreDevice()
    if name in ("dist_tpu_sync", "dist_sync", "dist_device_sync", "dist"):
        return KVStoreTPUSync()
    if name == "dist_async":
        raise ValueError(
            "dist_async (parameter-server async mode) is unsupported on TPU "
            "by design: XLA SPMD collectives are synchronous. Use "
            "dist_tpu_sync. (documented divergence, SURVEY §2.3)")
    raise ValueError("Unknown KVStore type %s" % name)
