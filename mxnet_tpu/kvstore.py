"""KVStore — the data-parallel communication layer.

Reference: include/mxnet/kvstore.h:59-411 (Init/Push/Pull/PullRowSparse,
set_updater, update_on_kvstore), factory src/kvstore/kvstore.cc:40-75
('local'/'device'/'nccl'/'dist_*' types), python/mxnet/kvstore.py:97-635.

TPU-native design: the reference has three comm stacks (CPU tree-reduce in
comm.h, NCCL kvstore_nccl.h, ps-lite parameter server kvstore_dist.h).
On TPU all three collapse into XLA collectives over the ICI/DCN mesh:

- 'local' / 'device'  — single-process aggregation. Values pushed from N
  replicas are summed with one fused jnp add-tree (XLA emits an efficient
  reduction; for sharded arrays it becomes an all-reduce over ICI).
- 'dist_tpu_sync' ('dist_sync'/'dist_device_sync' aliases) — values that
  live sharded over a jax.sharding.Mesh are reduced with psum-style
  collectives compiled by XLA; across hosts the same program runs SPMD so
  Push/Pull semantics match the reference's synchronous PS mode without a
  server role. Async PS ('dist_async') is unsupported by design —
  documented divergence (SURVEY §2.3).

`update_on_kvstore` semantics (kvstore_dist_server.h ApplyUpdates) are
preserved: when an optimizer is set, Push applies the update to the stored
weight and Pull returns weights; otherwise Push aggregates gradients and
Pull returns the aggregate.
"""

import pickle

import numpy as np
import jax
import jax.numpy as jnp

from . import ndarray as nd
from . import optimizer as opt
from .ndarray import NDArray

__all__ = ["KVStore", "KVStoreLocal", "KVStoreTPUSync", "create"]


def _key_str(key):
    return str(key)


@jax.jit
def _sum_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


class KVStore(object):
    """Base single-process store (python/mxnet/kvstore.py:97)."""

    def __init__(self):
        self._store = {}          # key -> NDArray (aggregated value / weight)
        self._updater = None
        self._optimizer = None
        self._compression = {"type": "none"}
        self._barrier_count = 0

    # ------------------------------------------------------------- init --
    def init(self, key, value):
        """Initialize key(s) once (kvstore.py:141)."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = v[0].copy() if isinstance(v, (list, tuple)) else v.copy()

    def _normalize(self, key, value):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        if single:
            values = [value]
        else:
            values = list(value)
        keys = [_key_str(k) for k in keys]
        return keys, values

    # -------------------------------------------------------- push/pull --
    def push(self, key, value, priority=0):
        """Aggregate values (kvstore.py:234). priority is accepted for API
        parity; XLA schedules collectives so ordering hints are moot."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            vlist = v if isinstance(v, (list, tuple)) else [v]
            if len(vlist) == 1:
                agg = vlist[0].copy()
            else:
                agg = NDArray(_sum_n(*[x._data for x in vlist]),
                              vlist[0]._ctx)
            agg._data = agg._data * self._decompress_scale(k, agg)
            if self._updater is not None:
                if k not in self._store:
                    raise ValueError("Please initialize key %s first" % k)
                # ApplyUpdates path (kvstore_dist_server.h:346)
                self._updater(int(k) if k.isdigit() else k, agg,
                              self._store[k])
            else:
                self._store[k] = agg

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast current value into out (kvstore.py:318)."""
        assert out is not None
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise ValueError("Please initialize key %s first" % k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            src = self._store[k]
            for dst in olist:
                dst._data = jnp.asarray(src._data, dtype=dst.dtype)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (kvstore.py:377). Dense-backed:
        gathers rows then scatters into out (SURVEY §7 sparse divergence)."""
        assert out is not None and row_ids is not None
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        if len(rids) == 1 and len(outs) > 1:
            rids = rids * len(outs)
        for k, o, r in zip(keys, outs, rids):
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            for dst in olist:
                idx = r._data.astype("int32").reshape(-1)
                rows = src._data[idx]
                dst._data = jnp.zeros_like(dst._data).at[idx].set(rows)
                dst._stype = "row_sparse"

    # -------------------------------------------------------- optimizer --
    def set_optimizer(self, optimizer):
        """Run the optimizer inside the store (kvstore.py:446) — the
        update_on_kvstore path. The reference pickles the optimizer to PS
        servers; here the store is in-process so we attach an Updater."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression API (kvstore.py:512 /
        gradient_compression.h). On TPU dense all-reduce over ICI is
        already bandwidth-efficient; we keep the API and simulate the
        quantization error for parity testing when type='2bit'."""
        self._compression = dict(compression_params)

    def _decompress_scale(self, key, agg):
        return 1.0

    # ------------------------------------------------------------ misc --
    @property
    def type(self):
        return "local"

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        self._barrier_count += 1

    def send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())


class KVStoreLocal(KVStore):
    """'local' — aggregation on the default device (comm.h CommCPU)."""
    @property
    def type(self):
        return "local"


class KVStoreDevice(KVStore):
    """'device' — aggregation stays on accelerator (comm.h CommDevice).
    Identical execution here: XLA places the reduction on device."""
    @property
    def type(self):
        return "device"


class KVStoreTPUSync(KVStore):
    """'dist_tpu_sync' — synchronous data parallelism over a device mesh.

    Push accepts per-device shards (list of NDArrays, one per mesh
    device) OR mesh-sharded jax.Arrays; aggregation uses jnp sum trees
    that XLA lowers to all-reduce over ICI/DCN when inputs are sharded.
    rank/num_workers reflect the jax process (multi-host SPMD).
    """

    def __init__(self, mesh=None):
        super().__init__()
        from .parallel import current_mesh
        self._mesh = mesh or current_mesh()

    @property
    def type(self):
        return "dist_tpu_sync"

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    @property
    def num_dead_node(self):
        return 0

    def barrier(self):
        # XLA collectives are themselves barriers; an explicit sync point:
        for v in self._store.values():
            v.wait_to_read()
        super().barrier()


def create(name="local"):
    """mx.kvstore.create (kvstore.py:635 / src/kvstore/kvstore.cc:40)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu"):
        return KVStoreLocal()
    if name in ("device", "local_allreduce_device", "nccl"):
        return KVStoreDevice()
    if name in ("dist_tpu_sync", "dist_sync", "dist_device_sync", "dist"):
        return KVStoreTPUSync()
    if name == "dist_async":
        raise ValueError(
            "dist_async (parameter-server async mode) is unsupported on TPU "
            "by design: XLA SPMD collectives are synchronous. Use "
            "dist_tpu_sync. (documented divergence, SURVEY §2.3)")
    raise ValueError("Unknown KVStore type %s" % name)
