"""Runtime feature detection (reference: python/mxnet/runtime.py and
src/libinfo.cc:39-90 — compile-time USE_* flags surfaced at runtime).

TPU-native equivalents: features reflect what this build actually
provides (XLA/TPU/Pallas/mesh collectives) plus the reference flag names
that map onto them; CUDA-era flags report disabled."""

import collections
import jax

__all__ = ["Feature", "feature_list", "Features"]

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    try:
        platform = jax.default_backend()
        has_tpu = platform == "tpu" or any(
            d.platform == "tpu" for d in jax.devices())
    except Exception:  # pragma: no cover - backend init failure
        has_tpu = False
    try:
        import cv2  # noqa: F401
        has_cv = True
    except ImportError:
        has_cv = False
    feats = {
        # TPU-native capabilities
        "TPU": has_tpu,
        "XLA": True,
        "PALLAS": True,
        "MESH_COLLECTIVES": True,
        "BF16": True,
        # reference flag names (src/libinfo.cc) mapped to this build
        "CUDA": False, "CUDNN": False, "NCCL": False, "TENSORRT": False,
        "MKLDNN": False, "OPENMP": False, "BLAS_OPEN": False,
        "CPU_SSE": False, "CPU_AVX": False,
        "OPENCV": has_cv,
        "DIST_KVSTORE": True,   # dist_tpu_sync over XLA collectives
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": False,
        "PROFILER": True,
    }
    return feats


class Features(dict):
    """dict of name -> Feature with `is_enabled` (reference
    runtime.Features)."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super(Features, cls).__new__(cls)
            dict.__init__(cls.instance,
                          [(n, Feature(n, e))
                           for n, e in _detect().items()])
        return cls.instance

    def __repr__(self):
        return "[%s]" % ", ".join(
            "%s%s" % ("✔ " if f.enabled else "✖ ", f.name)
            for f in self.values())

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("Feature '%s' is unknown, known features "
                               "are: %s" % (feature_name, list(self)))
        return self[feature_name].enabled


def feature_list():
    """List of Feature tuples (reference mx.runtime.feature_list)."""
    return list(Features().values())
