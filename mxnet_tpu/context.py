"""Device contexts.

Reference: python/mxnet/context.py — Context(device_type, device_id) with
`with ctx:` scoping and a thread-default. TPU-native mapping: a Context wraps
a concrete `jax.Device`. `gpu(i)` is accepted for source compatibility and
resolves to the i-th accelerator (TPU) when one exists.
"""

import threading

import jax

from ._discover import ensure_backend

_thread_local = threading.local()


class Context:
    """Device context, usable as a `with` scope (python/mxnet/context.py:28)."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        # NOTE: no ensure_backend() here — Contexts are constructed at
        # import time (model_zoo ctx=cpu() default args) and must stay
        # free of backend discovery; the guard runs at device RESOLUTION
        # (jax_device/_accelerators) and in ndarray._resolve_ctx.
        if isinstance(device_type, Context):
            self.device_type, self.device_id = device_type.device_type, device_type.device_id
        else:
            self.device_type = device_type
            self.device_id = device_id
        self._old_ctx = None

    # -- identity ---------------------------------------------------------
    @property
    def device_typeid(self):
        return self.devstr2type[self.device_type]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    # -- jax mapping ------------------------------------------------------
    @property
    def jax_device(self):
        """The concrete jax.Device this context denotes."""
        ensure_backend()  # wedge-proof first discovery (_discover.py)
        if self.device_type == "cpu" or self.device_type == "cpu_pinned" \
                or self.device_type == "cpu_shared":
            devs = _devices_by_platform("cpu")
        else:
            devs = _accelerators()
            if not devs:  # no accelerator present: transparently run on host
                devs = _devices_by_platform("cpu")
        if not devs:
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Release pooled memory (reference Context.empty_cache). XLA manages
        HBM arenas itself; provided as a no-op hook."""

    # -- scoping ----------------------------------------------------------
    def __enter__(self):
        if not hasattr(_thread_local, "ctx_stack"):
            _thread_local.ctx_stack = []
        _thread_local.ctx_stack.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        _thread_local.ctx_stack.pop()

    @classmethod
    def default_ctx(cls):
        stack = getattr(_thread_local, "ctx_stack", None)
        if stack:
            return stack[-1]
        return _default_context()


def _devices_by_platform(platform):
    """Devices a Context index may denote. In a multi-process SPMD job
    only THIS process's devices are addressable for eager placement, so
    cpu(0)/tpu(0) means local device 0 (reference semantics: each worker
    sees its own GPUs); the global mesh is the parallel layer's job."""
    ensure_backend()  # wedge-proof first discovery (_discover.py)
    try:
        if jax.process_count() > 1:
            return [d for d in jax.local_devices()
                    if d.platform == platform]
        return jax.devices(platform)
    except RuntimeError:
        return []


def _accelerators():
    ensure_backend()  # wedge-proof first discovery (_discover.py)
    if jax.process_count() > 1:
        return [d for d in jax.local_devices() if d.platform != "cpu"]
    return [d for d in jax.devices() if d.platform != "cpu"]


def _default_context():
    if _accelerators():
        return Context("tpu", 0)
    return Context("cpu", 0)


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def gpu(device_id=0):
    """Source-compat alias: reference scripts say `mx.gpu(0)`; on this stack
    it denotes the i-th accelerator (TPU) chip."""
    return Context("gpu", device_id)


def num_gpus():
    return len(_accelerators())


def num_tpus():
    return len(_accelerators())


def current_context():
    return Context.default_ctx()


def gpu_memory_info(device_id=0):
    """(free, total) bytes of the accelerator (reference
    context.gpu_memory_info over cudaMemGetInfo)."""
    from .util import get_gpu_memory
    return get_gpu_memory(device_id)
