"""Network visualization (reference: python/mxnet/visualization.py —
print_summary and plot_network over the Symbol graph)."""

from .symbol import Symbol
from .base import MXNetError


def _src_name(input_sym):
    """Name of the node feeding an input slot (inputs hold
    (Symbol, out_index) pairs)."""
    ni, _ = input_sym._outputs[0]
    return input_sym._nodes[ni].name

__all__ = ["print_summary", "plot_network"]


def _elems(shp):
    n = 1
    for d in shp:
        n *= d
    return n


def _node_flops(node, shapes):
    """Shape-based per-node FLOP estimate (the fallback when no
    compiled executable is registered with the attribution layer):
    2*out*K for matmul/conv from the weight shape, one per output
    element for the elementwise-ish lanes, 0 where we can't say."""
    out_shape = shapes.get("%s#0" % node.name)
    if out_shape is None:
        return None
    out = _elems(out_shape)
    w_shape = None
    for inp, _ in node.inputs:
        name = _src_name(inp)
        if name.endswith("weight") and name in shapes:
            w_shape = shapes[name]
            break
    if node.op in ("FullyConnected", "dot", "linalg_gemm2"):
        if w_shape is not None and len(w_shape) >= 2:
            return 2.0 * out * w_shape[-1]
        return None
    if node.op in ("Convolution", "Deconvolution"):
        if w_shape is not None and w_shape:
            return 2.0 * out * _elems(w_shape) / max(w_shape[0], 1)
        return None
    if node.op in ("Activation", "relu", "sigmoid", "tanh", "softmax",
                   "SoftmaxOutput", "LeakyReLU", "elemwise_add",
                   "elemwise_mul", "broadcast_add", "broadcast_mul",
                   "_plus", "_mul", "Dropout"):
        return float(out)
    if node.op == "BatchNorm":
        return 2.0 * out        # scale + shift per element
    if node.op == "Pooling":
        return float(out)       # one accumulate per output element
    return 0.0


def print_summary(symbol, shape=None, line_length=120, positions=None,
                  flops=False):
    """Print a layer-by-layer summary table of a Symbol graph.

    ``flops=True`` adds a per-layer FLOPs column. When the attribution
    layer holds a compiled executable whose scopes match this graph's
    node names (MXNET_OBS=1 and the program already ran —
    docs/OBSERVABILITY.md "Per-operator attribution"), the column shows
    the measured per-scope totals from the optimized HLO (which include
    backward for `.step` programs); otherwise it falls back to
    shape-based per-node estimates (forward only, ``shape`` required
    for anything beyond matmul/conv with deferred shapes).
    """
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    positions = positions or (
        [0.38, 0.55, 0.64, 0.76, 1.0] if flops
        else [0.44, 0.64, 0.74, 1.0])
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]
    if flops:
        to_display.insert(3, "FLOPs")

    def print_row(fields, pos):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[: pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)

    shape_dict = {}
    node_shapes = {}
    if shape is not None:
        arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
        shape_dict = dict(zip(symbol.list_arguments(), arg_shapes))

    nodes = symbol._active_nodes()
    scope_flops = {}
    analyzed = False
    if flops:
        from .observability import attribution
        if attribution._programs:
            summ = attribution.summary()
            scope_flops = {name: ent["flops"]
                           for name, ent in summ["scopes"].items()}
            # the registered programs must actually cover THIS graph —
            # an unrelated executable's scopes fall back to estimates
            analyzed = any(n.name in scope_flops for n in nodes
                           if not n.is_var())
        if not analyzed and shape is not None:
            from .symbol import _infer_graph
            known = {k: tuple(v) for k, v in shape.items()}
            node_shapes, _ = _infer_graph(nodes, known, {},
                                          partial=True)

    total_params = 0
    total_flops = 0.0
    for node in nodes:
        name = node.name
        n_flops = None
        if node.is_var():
            op = "Variable"
            out_shape = shape_dict.get(name, "")
            params = 0
            if name in shape_dict and name != "data" \
                    and not name.endswith("label"):
                params = 1
                for d in shape_dict[name]:
                    params *= d
            prev = ""
        else:
            op = node.op
            out_shape = ""
            params = 0
            prev = ",".join(_src_name(inp) for inp, _ in node.inputs[:3])
            if flops:
                n_flops = scope_flops.get(name) if analyzed \
                    else _node_flops(node, node_shapes)
        total_params += params
        row = ["%s (%s)" % (name, op), str(out_shape), params, prev]
        if flops:
            if n_flops:
                total_flops += n_flops
                shown = "%.0f" % n_flops
            else:
                shown = "" if n_flops is None else "0"
            row.insert(3, shown)
        print_row(row, positions)
        print("_" * line_length)
    print("Total params: %d" % total_params)
    if flops:
        print("Total FLOPs: %.3e (%s)"
              % (total_flops,
                 "per-scope HLO analysis" if analyzed
                 else "shape-based estimate"))
    print("=" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz digraph of the Symbol (requires the python
    graphviz package; raises a clear error when absent)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError(
            "plot_network requires the graphviz python package")
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    node_attrs = node_attrs or {}
    dot = Digraph(name=title, format=save_format)
    hidden = set()
    for node in symbol._active_nodes():
        name = node.name
        if node.is_var():
            if hide_weights and name != "data" \
                    and not name.endswith("label"):
                hidden.add(name)
                continue
            dot.node(name, label=name, shape="oval")
        else:
            dot.node(name, label="%s\n%s" % (name, node.op), shape="box",
                     **node_attrs)
    for node in symbol._active_nodes():
        if node.is_var():
            continue
        for inp, _ in node.inputs:
            src_name = _src_name(inp)
            if src_name not in hidden:
                dot.edge(src_name, node.name)
    return dot
