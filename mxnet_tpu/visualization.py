"""Network visualization (reference: python/mxnet/visualization.py —
print_summary and plot_network over the Symbol graph)."""

from .symbol import Symbol
from .base import MXNetError


def _src_name(input_sym):
    """Name of the node feeding an input slot (inputs hold
    (Symbol, out_index) pairs)."""
    ni, _ = input_sym._outputs[0]
    return input_sym._nodes[ni].name

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary table of a Symbol graph."""
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, pos):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[: pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)

    shape_dict = {}
    if shape is not None:
        arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
        shape_dict = dict(zip(symbol.list_arguments(), arg_shapes))

    total_params = 0
    nodes = symbol._active_nodes()
    for node in nodes:
        name = node.name
        if node.is_var():
            op = "Variable"
            out_shape = shape_dict.get(name, "")
            params = 0
            if name in shape_dict and name != "data" \
                    and not name.endswith("label"):
                params = 1
                for d in shape_dict[name]:
                    params *= d
            prev = ""
        else:
            op = node.op
            out_shape = ""
            params = 0
            prev = ",".join(_src_name(inp) for inp, _ in node.inputs[:3])
        total_params += params
        print_row(["%s (%s)" % (name, op), str(out_shape), params, prev],
                  positions)
        print("_" * line_length)
    print("Total params: %d" % total_params)
    print("=" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz digraph of the Symbol (requires the python
    graphviz package; raises a clear error when absent)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError(
            "plot_network requires the graphviz python package")
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    node_attrs = node_attrs or {}
    dot = Digraph(name=title, format=save_format)
    hidden = set()
    for node in symbol._active_nodes():
        name = node.name
        if node.is_var():
            if hide_weights and name != "data" \
                    and not name.endswith("label"):
                hidden.add(name)
                continue
            dot.node(name, label=name, shape="oval")
        else:
            dot.node(name, label="%s\n%s" % (name, node.op), shape="box",
                     **node_attrs)
    for node in symbol._active_nodes():
        if node.is_var():
            continue
        for inp, _ in node.inputs:
            src_name = _src_name(inp)
            if src_name not in hidden:
                dot.edge(src_name, node.name)
    return dot
