"""Optimizers.

Reference: python/mxnet/optimizer/optimizer.py:48-1672 (Optimizer base with
registry + 17 optimizers) and the fused C++ update kernels in
src/operator/optimizer_op.cc:47-893.

TPU-native design: each update rule is a pure jnp function jit-compiled by
XLA (the analogue of the fused `sgd_mom_update`/`adam_update` kernels —
XLA fuses the elementwise chain into one HBM pass). Hyper-parameters that
change per step (lr, wd, rescale) are passed as traced scalars so a
changing schedule never recompiles. States live as jax.Arrays inside
NDArrays, matching `create_state`/`update` semantics that kvstore's
server-side Updater also consumes.
"""

import math
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from . import ndarray as nd
from .ndarray import NDArray
from .base import MXNetError

__all__ = ["Optimizer", "SGD", "NAG", "Signum", "FTML", "DCASGD", "LBSGD",
           "SGLD", "Adam", "AdaGrad", "AdaDelta", "RMSProp", "Ftrl",
           "Adamax", "Nadam", "Test", "Updater", "get_updater", "create",
           "register"]

_OPT_REGISTRY = {}


def register(klass):
    """Optimizer.register decorator (optimizer.py:93)."""
    name = klass.__name__.lower()
    _OPT_REGISTRY[name] = klass
    return klass


def create(name, **kwargs):
    """mx.optimizer.create (optimizer.py:139)."""
    if name.lower() not in _OPT_REGISTRY:
        raise ValueError("Cannot find optimizer %s" % name)
    return _OPT_REGISTRY[name.lower()](**kwargs)


def _align_update_devices(weight, grad, state):
    """Reconcile weight/grad device placement before a fused update.

    Data-parallel training with a batch sharded over a Mesh produces
    grads committed to the mesh (replicated — XLA inserted the psum),
    while weights initialized before the mesh existed sit committed to
    one device; jit refuses to mix them. Promote the weight (and its
    optimizer state) onto the wider device set — the update then runs
    replicated on the mesh with no per-step broadcast, the sharded-
    global-array analogue of the reference's per-device weight copies
    (module/executor_group.py DP semantics). If instead the WEIGHT
    spans more devices, bring the grad to it (pull-to-master)."""
    gdata = getattr(grad, "_data", None)
    wdata = getattr(weight, "_data", None)
    gs = getattr(gdata, "sharding", None)
    ws = getattr(wdata, "sharding", None)
    if gs is None or ws is None:
        return grad
    try:
        gdev, wdev = gs.device_set, ws.device_set
    except AttributeError:
        return grad
    if gdev == wdev:
        # weight/grad agree, but state buffers created lazily by the
        # Updater land on the default device — align them to the
        # weight's (possibly mesh-replicated) sharding or the fused
        # update kernel refuses the device mix
        _align_state_tree(state, ws)
        return grad
    if len(gdev) > len(wdev):
        weight._data = jax.device_put(wdata, gs)
        _align_state_tree(state, gs)
    else:
        # shallow wrapper: the caller's grad must stay untouched, but
        # the moved buffer needs no copy of the original
        grad = NDArray(jax.device_put(gdata, ws), grad.context)
    return grad


def _align_state_tree(state, sharding):
    if state is None:
        return
    if isinstance(state, (tuple, list)):
        for s in state:
            _align_state_tree(s, sharding)
        return
    data = getattr(state, "_data", None)
    if data is not None and getattr(data, "sharding", None) is not None \
            and data.sharding.device_set != sharding.device_set:
        state._data = jax.device_put(data, sharding)


def _flt(x):
    return jnp.asarray(x, dtype=jnp.float32)


class Optimizer(object):
    """Base optimizer (optimizer.py:48): lr/wd multipliers resolved per
    param index, gradient rescale + clip, update-count tracking for
    schedulers and bias correction."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        if lr_scheduler is not None:
            lr_scheduler.base_lr = learning_rate
        assert param_idx2name is None or isinstance(param_idx2name, dict)
        self.__dict__.update(
            rescale_grad=rescale_grad, lr=learning_rate,
            lr_scheduler=lr_scheduler, wd=wd,
            begin_num_update=begin_num_update,
            num_update=begin_num_update, _index_update_count={},
            clip_gradient=clip_gradient,
            multi_precision=multi_precision, aggregate_num=0,
            idx2name=dict(param_idx2name or {}),
            sym_info=(sym.attr_dict(), sym.list_arguments())
            if sym is not None else (),
            param_dict=param_dict or {})
        self.set_lr_mult({})
        self.set_wd_mult({})

    create_optimizer = staticmethod(create)
    opt_registry = _OPT_REGISTRY

    @staticmethod
    def register(klass):
        return register(klass)

    def _take(self, **hyper):
        """Bind rule hyperparameters as attributes in one shot."""
        self.__dict__.update(hyper)

    # ------------------------------------------------------------ state --
    def create_state(self, index, weight):
        return None

    def _zeros_like(self, weight, dtype=None):
        """Fresh state buffer shaped/placed like the weight."""
        return nd.zeros(weight.shape, weight.context,
                        dtype=dtype or weight.dtype)

    def create_state_multi_precision(self, index, weight):
        """fp32 master copy for bf16 weights (optimizer.py:278)."""
        if self.multi_precision and weight.dtype == jnp.bfloat16:
            master = weight.astype("float32")
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        """Apply one step. The base implementation is a template: it
        advances the per-index step count, resolves the scheduled/
        multiplied hyperparameters, and hands off to the subclass's
        ``_apply_rule`` — so rule implementations hold ONLY math.
        Subclasses may still override update() wholesale (the
        reference's extension contract, honored for external code)."""
        self._update_count(index)
        self._apply_rule(self._index_update_count[index],
                         self._get_lr(index), self._get_wd(index),
                         weight, grad, state)

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        grad = _align_update_devices(weight, grad, state)
        if self.multi_precision and weight.dtype == jnp.bfloat16:
            weight_master_copy, original_state = state
            grad32 = grad.astype("float32")
            self.update(index, weight_master_copy, grad32, original_state)
            weight._data = weight_master_copy._data.astype(jnp.bfloat16)
        else:
            # keep the weight's storage dtype: fp32 state/lr arithmetic
            # promotes bf16 weights to fp32 inside update(), and writing
            # that back would silently un-cast a low-precision network
            wdtype = weight.dtype
            self.update(index, weight, grad, state)
            if weight.dtype != wdtype:
                weight._data = weight._data.astype(wdtype)

    # -------------------------------------------------------- lr/wd mult --
    @property
    def learning_rate(self):
        """Current base lr (optimizer.py learning_rate property)."""
        return self.lr if self.lr_scheduler is None \
            else self.lr_scheduler(self.num_update)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already "
                              "been defined; setting lr directly would "
                              "be overridden at the next update")
        self.lr = lr

    def _sym_multipliers(self, attr_key):
        """Per-name multipliers declared as symbol attributes
        (``__lr_mult__`` / ``__wd_mult__``) when the optimizer was built
        from a Symbol."""
        if not self.sym_info:
            return {}
        attrs, arg_names = self.sym_info
        found = ((name, attrs.get(name, {}).get(attr_key))
                 for name in arg_names)
        return {name: float(mult) for name, mult in found
                if mult is not None}

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = self._sym_multipliers("__lr_mult__")
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        # decay applies to weights and BN gammas; every other named
        # param (bias, beta, moving stats) defaults to no decay
        self.wd_mult = {n: 0.0 for n in self.idx2name.values()
                        if not n.endswith(("_weight", "_gamma"))}
        self.wd_mult.update(self._sym_multipliers("__wd_mult__"))
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        indices = index if isinstance(index, (list, tuple)) else (index,)
        for idx in indices:
            seen = self._index_update_count.get(idx,
                                                self.begin_num_update) + 1
            self._index_update_count[idx] = seen
            if seen > self.num_update:
                self.num_update = seen

    def _scaled_hyper(self, indices, base, which):
        """``base`` scaled by each param's multiplier. Precedence: the
        Parameter object's own mult (param_dict, Gluon path), then an
        explicit per-index entry, then the index's resolved name in the
        mult table (Module path); absent everywhere = 1."""
        table = getattr(self, which + "_mult")
        out = []
        for index in indices:
            if index in self.param_dict:
                mult = getattr(self.param_dict[index], which + "_mult")
            elif index in table:
                mult = table[index]
            else:
                mult = table.get(self.idx2name.get(index), 1.0)
            out.append(base * mult)
        return out

    def _get_lrs(self, indices):
        base = self.lr_scheduler(self.num_update) \
            if self.lr_scheduler is not None else self.lr
        return self._scaled_hyper(indices, base, "lr")

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        return self._scaled_hyper(indices, self.wd, "wd")

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def _preprocess_grad(self, grad):
        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def _sparse_rows(self, grad):
        """(row_indices, row_grads) when grad is row_sparse, else None —
        enables lazy updates touching only referenced rows (reference
        sparse sgd/adagrad kernels, optimizer_op.cc:47-893)."""
        from .sparse import RowSparseNDArray
        if not isinstance(grad, RowSparseNDArray):
            return None
        g = grad._sp_data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return grad._sp_indices, g

# --------------------------------------------------------------- rules ---
# Pure jitted update kernels (analogues of src/operator/optimizer_op.cc).

@jax.jit
def _sgd_update(w, g, lr, wd):
    return w - lr * (g + wd * w)


@jax.jit
def _sgd_mom_update(w, g, mom, lr, wd, momentum):
    mom = momentum * mom - lr * (g + wd * w)
    return w + mom, mom


@jax.jit
def _nag_mom_update(w, g, mom, lr, wd, momentum):
    g = g + wd * w
    mom = momentum * mom + g
    return w - lr * (momentum * mom + g), mom


@jax.jit
def _adam_update(w, g, m, v, lr, wd, beta1, beta2, eps):
    g = g + wd * w
    m = beta1 * m + (1.0 - beta1) * g
    v = beta2 * v + (1.0 - beta2) * g * g
    return w - lr * m / (jnp.sqrt(v) + eps), m, v


@register
class SGD(Optimizer):
    """SGD with momentum and optional multi-precision (optimizer.py:479;
    kernels optimizer_op.cc sgd_update/sgd_mom_update). lazy_update applies
    only to row_sparse — dense-backed here, so it is a no-op flag."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self._take(momentum=momentum, lazy_update=lazy_update)

    def create_state(self, index, weight):
        return self._zeros_like(weight) if self.momentum else None

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        sparse = self._sparse_rows(grad) if self.lazy_update else None
        if sparse is not None:
            rows, g = sparse
            w = weight._data
            if state is not None:
                m = state._data[rows] * self.momentum - lr * (
                    g + wd * w[rows])
                state._data = state._data.at[rows].set(m)
                weight._data = w.at[rows].add(m)
            else:
                weight._data = w.at[rows].add(-lr * (g + wd * w[rows]))
            return
        g = self._preprocess_grad(grad)
        if state is not None:
            weight._data, state._data = _sgd_mom_update(
                weight._data, g, state._data, _flt(lr), _flt(wd),
                _flt(self.momentum))
        else:
            weight._data = _sgd_update(weight._data, g, _flt(lr), _flt(wd))


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (optimizer.py:1137)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self._take(momentum=momentum)

    def create_state(self, index, weight):
        return self._zeros_like(weight) if self.momentum else None

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad)
        if state is not None:
            weight._data, state._data = _nag_mom_update(
                weight._data, g, state._data, _flt(lr), _flt(wd),
                _flt(self.momentum))
        else:
            weight._data = _sgd_update(weight._data, g, _flt(lr), _flt(wd))


@register
class Signum(Optimizer):
    """signSGD / Signum (optimizer.py:699): takes sign of (momentum) grad."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self._take(momentum=momentum, wd_lh=wd_lh)

    def create_state(self, index, weight):
        return self._zeros_like(weight) if self.momentum else None

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad)
        if state is not None:
            mom = self.momentum * state._data - (1 - self.momentum) * (g + wd * weight._data)
            weight._data = (1 - lr * self.wd_lh) * weight._data + lr * jnp.sign(mom)
            state._data = mom
        else:
            weight._data = (1 - lr * (self.wd_lh + wd)) * weight._data \
                - lr * jnp.sign(g)


@register
class FTML(Optimizer):
    """Follow the Moving Leader (optimizer.py:636)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self._take(beta1=beta1, beta2=beta2, epsilon=epsilon)

    def create_state(self, index, weight):
        z = (self._zeros_like(weight),
             self._zeros_like(weight),
             self._zeros_like(weight))
        return z  # (prev_d, prev_v, prev_z)

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad) + wd * weight._data
        prev_d, prev_v, prev_z = state
        v = self.beta2 * prev_v._data + (1 - self.beta2) * g * g
        d = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d - self.beta1 * prev_d._data
        z = self.beta1 * prev_z._data + (1 - self.beta1) * g \
            - sigma * weight._data
        weight._data = -z / d
        prev_d._data, prev_v._data, prev_z._data = d, v, z


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (optimizer.py:769)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self._take(momentum=momentum, lamda=lamda, weight_previous={})

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (self._zeros_like(weight),
                weight.copy())

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad)
        mon, previous_weight = state
        comp = g + wd * weight._data + self.lamda * g * g * \
            (weight._data - previous_weight._data)
        if mon is not None:
            mon._data = self.momentum * mon._data - lr * comp
            delta = mon._data
        else:
            delta = -lr * comp
        previous_weight._data = weight._data
        weight._data = weight._data + delta


@register
class LBSGD(Optimizer):
    """Large-batch SGD with LARS-style layer-wise adaptive rate + warmup
    (optimizer.py:860). Simplified: warmup strategies collapse to 'linear'
    scaling of lr; adaptive ratio = ||w||/||g|| as in the reference."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self._take(momentum=momentum, warmup_strategy=warmup_strategy,
                   warmup_epochs=warmup_epochs, batch_scale=batch_scale,
                   updates_per_epoch=updates_per_epoch,
                   init_updates=begin_epoch * updates_per_epoch,
                   num_epochs=num_epochs,
                   adaptive=warmup_strategy.startswith("lars"))

    def create_state(self, index, weight):
        return self._zeros_like(weight) if self.momentum else None

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad)
        if self.adaptive:
            wnorm = jnp.linalg.norm(weight._data)
            gnorm = jnp.linalg.norm(g)
            ratio = jnp.where(gnorm > 0, wnorm / (gnorm + wd * wnorm + 1e-9), 1.0)
            lr = lr * jnp.clip(ratio, 0.0, 10.0)
        if state is not None:
            weight._data, state._data = _sgd_mom_update(
                weight._data, g, state._data, _flt(lr), _flt(wd),
                _flt(self.momentum))
        else:
            weight._data = _sgd_update(weight._data, g, _flt(lr), _flt(wd))


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (optimizer.py:1599)."""

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad)
        noise = nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype="float32")
        weight._data = weight._data - lr / 2 * (g + wd * weight._data) \
            + noise._data.astype(weight.dtype)


@register
class Adam(Optimizer):
    """Adam (optimizer.py:1181; kernel optimizer_op.cc adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self._take(beta1=beta1, beta2=beta2, epsilon=epsilon,
                   lazy_update=lazy_update)

    def create_state(self, index, weight):
        return (self._zeros_like(weight),
                self._zeros_like(weight))

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        g = self._preprocess_grad(grad)
        mean, var = state
        weight._data, mean._data, var._data = _adam_update(
            weight._data, g, mean._data, var._data, _flt(lr), _flt(wd),
            _flt(self.beta1), _flt(self.beta2), _flt(self.epsilon))


@register
class AdaGrad(Optimizer):
    """AdaGrad (optimizer.py:1369; sparse adagrad in optimizer_op.cc:893)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self._take(float_stable_eps=eps)

    def create_state(self, index, weight):
        return self._zeros_like(weight)

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        sparse = self._sparse_rows(grad)
        if sparse is not None:
            # sparse adagrad (optimizer_op.cc:893): history/update only on
            # referenced rows
            rows, g = sparse
            g = g + wd * weight._data[rows]
            hist = state._data[rows] + g * g
            state._data = state._data.at[rows].set(hist)
            weight._data = weight._data.at[rows].add(
                -lr * g / (jnp.sqrt(hist) + self.float_stable_eps))
            return
        g = self._preprocess_grad(grad) + wd * weight._data
        state._data = state._data + g * g
        weight._data = weight._data - lr * g / (
            jnp.sqrt(state._data) + self.float_stable_eps)


@register
class AdaDelta(Optimizer):
    """AdaDelta (optimizer.py:1467)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._take(rho=rho, epsilon=epsilon)

    def create_state(self, index, weight):
        return (self._zeros_like(weight),
                self._zeros_like(weight))

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad) + wd * weight._data
        acc_g, acc_delta = state
        acc_g._data = self.rho * acc_g._data + (1. - self.rho) * g * g
        delta = jnp.sqrt(acc_delta._data + self.epsilon) / \
            jnp.sqrt(acc_g._data + self.epsilon) * g
        acc_delta._data = self.rho * acc_delta._data + (1. - self.rho) * delta * delta
        weight._data = weight._data - delta


@register
class RMSProp(Optimizer):
    """RMSProp, non-centered (Hinton) and centered (Graves) variants
    (optimizer.py:1270)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self._take(gamma1=gamma1, gamma2=gamma2, centered=centered,
                   epsilon=epsilon, clip_weights=clip_weights)

    def create_state(self, index, weight):
        if self.centered:
            return (self._zeros_like(weight),
                    self._zeros_like(weight),
                    self._zeros_like(weight))
        return (self._zeros_like(weight),)

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad) + wd * weight._data
        if self.centered:
            n, gmean, delta = state
            n._data = (1 - self.gamma1) * g * g + self.gamma1 * n._data
            gmean._data = (1 - self.gamma1) * g + self.gamma1 * gmean._data
            delta._data = self.gamma2 * delta._data - lr * g / jnp.sqrt(
                n._data - gmean._data * gmean._data + self.epsilon)
            weight._data = weight._data + delta._data
        else:
            (n,) = state
            n._data = (1 - self.gamma1) * g * g + self.gamma1 * n._data
            weight._data = weight._data - lr * g / jnp.sqrt(n._data + self.epsilon)
        if self.clip_weights:
            weight._data = jnp.clip(weight._data, -self.clip_weights,
                                    self.clip_weights)


@register
class Ftrl(Optimizer):
    """FTRL-proximal (optimizer.py:1518)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self._take(lamda1=lamda1, beta=beta)

    def create_state(self, index, weight):
        return (self._zeros_like(weight),  # z
                self._zeros_like(weight))  # n

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad)
        z, n = state
        sigma = (jnp.sqrt(n._data + g * g) - jnp.sqrt(n._data)) / lr
        z._data = z._data + g - sigma * weight._data
        n._data = n._data + g * g
        weight._data = jnp.where(
            jnp.abs(z._data) <= self.lamda1,
            jnp.zeros_like(weight._data),
            -(z._data - jnp.sign(z._data) * self.lamda1) /
            ((self.beta + jnp.sqrt(n._data)) / lr + wd))


@register
class Adamax(Optimizer):
    """AdaMax (optimizer.py:1613)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self._take(beta1=beta1, beta2=beta2)

    def create_state(self, index, weight):
        return (self._zeros_like(weight),
                self._zeros_like(weight))

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        lr /= (1. - self.beta1 ** t)
        g = self._preprocess_grad(grad) + wd * weight._data
        m_t, u_t = state
        m_t._data = self.beta1 * m_t._data + (1. - self.beta1) * g
        u_t._data = jnp.maximum(self.beta2 * u_t._data, jnp.abs(g))
        weight._data = weight._data - lr * m_t._data / (u_t._data + 1e-12)


@register
class Nadam(Optimizer):
    """Nesterov Adam (optimizer.py:1660)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self._take(beta1=beta1, beta2=beta2, epsilon=epsilon,
                   schedule_decay=schedule_decay, m_schedule=1.)

    def create_state(self, index, weight):
        return (self._zeros_like(weight),
                self._zeros_like(weight))

    def _apply_rule(self, t, lr, wd, weight, grad, state):
        g = self._preprocess_grad(grad) + wd * weight._data
        momentum_t = self.beta1 * (1. - 0.5 * (pow(0.96, t * self.schedule_decay)))
        momentum_t_1 = self.beta1 * (1. - 0.5 *
                                     (pow(0.96, (t + 1) * self.schedule_decay)))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t._data = self.beta1 * m_t._data + (1. - self.beta1) * g
        v_t._data = self.beta2 * v_t._data + (1. - self.beta2) * g * g
        grad_prime = g / (1. - self.m_schedule)
        m_t_prime = m_t._data / (1. - m_schedule_next)
        v_t_prime = v_t._data / (1. - pow(self.beta2, t))
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        weight._data = weight._data - lr * m_t_bar / (
            jnp.sqrt(v_t_prime) + self.epsilon)


@register
class Test(Optimizer):
    """Test optimizer that stores the weight delta (optimizer.py:437)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight._data = weight._data + grad._data * self.rescale_grad
        state._data = weight._data


# alias used in examples (ccSGD was deprecated alias of SGD in 1.x)
_OPT_REGISTRY["ccsgd"] = SGD


class Updater(object):
    """Applies an optimizer to (index, grad, weight) triples — the object
    the reference ships to kvstore servers (optimizer.py get_updater /
    kvstore_dist_server.h ApplyUpdates)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        batched = isinstance(index, (list, tuple))
        triples = zip(index, grad, weight) if batched \
            else ((index, grad, weight),)
        for i, g, w in triples:
            if i not in self.states:
                self.states[i] = \
                    self.optimizer.create_state_multi_precision(i, w)
                self.states_synced[i] = True
            self.optimizer.update_multi_precision(i, w, g,
                                                  self.states[i])

    def get_states(self, dump_optimizer=False):
        payload = (self.states, self.optimizer) if dump_optimizer \
            else self.states
        return pickle.dumps(payload)

    def set_states(self, states):
        loaded = pickle.loads(states)
        # two wire formats: bare state dict, or (states, optimizer)
        # when the sender dumped its optimizer too
        if isinstance(loaded, tuple) and len(loaded) == 2:
            self.states, self.optimizer = loaded
        else:
            self.states = loaded
        self.states_synced = {i: False for i in self.states}


def get_updater(optimizer):
    """mx.optimizer.get_updater (optimizer.py end)."""
    return Updater(optimizer)


@register
class ccSGD(SGD):
    """Deprecated reference alias of SGD (optimizer.py ccSGD) — kept so
    old configs creating 'ccsgd' resolve."""
