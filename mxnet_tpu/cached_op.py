"""CachedOp — compiled trace for Gluon hybridize.

Reference: src/imperative/cached_op.{cc,h} (CachedOp::Forward:904,
DynamicForward:815, StaticForward:742, Backward:1128) — there, the traced
graph is replayed through the dependency engine with optional
static_alloc/static_shape memory planning.

TPU-native design: the traced Symbol is lowered to ONE jit-compiled XLA
computation per (is_train, shapes, dtypes, diff-set) signature via
executor.build_graph_fn. XLA subsumes static_alloc/static_shape (buffer
assignment), op bulking (fusion) and the backward-graph pass (jax.vjp).
Autograd integration records a single tape node whose pullback is the
compiled transpose of the whole computation — the reference's
CachedOp::Backward analogue.
"""

import jax
import jax.numpy as jnp

from . import autograd
from . import engine as _engine
from . import random as _random
from .base import MXNetError
from .executor import apply_mirror, build_graph_fn, mirror_enabled
from .observability import attribution as _obs_attr
from .observability import core as _obs
from .observability import membudget as _membudget
from .observability import recompile as _obs_recompile

# fixed key fed to RNG-free graphs (never consumed; avoids a per-call
# host-side split)
_ZERO_KEY = None


@jax.jit
def _apply_vjp(vjp, ct):
    (grads,) = vjp(ct)
    return grads


def _zero_key():
    global _ZERO_KEY
    if _ZERO_KEY is None:
        _ZERO_KEY = jax.random.PRNGKey(0)
    return _ZERO_KEY


class CachedOp:
    """Compiled callable over a Symbol.

    ``__call__(*inputs)`` takes NDArrays ordered as ``sym.list_inputs()``
    (arguments and auxiliary states in declaration order), mirroring
    MXInvokeCachedOp (src/c_api/c_api_ndarray.cc:192). Auxiliary states
    (e.g. BatchNorm running stats) are updated in place on the passed
    NDArrays after each call.
    """

    def __init__(self, sym, flags=()):
        from . import ops as _ops
        self._sym = sym
        self._flags = dict(flags) if flags else {}
        self._arg_names = sym.list_arguments()
        self._aux_names = sym.list_auxiliary_states()
        self._input_names = sym.list_inputs()
        self._num_outputs = len(sym.list_outputs())
        # (is_train, diff_names, nan_guard, mirror) -> jitted fn;
        # guard/mirror toggles force a retrace on purpose
        self._fns = {}
        # RNG-free graphs (the common case) skip the per-call host-side
        # key split — a measurable slice of per-call latency
        # (benchmark/opperf.py --dispatch)
        self._needs_rng = any(
            _ops.get(n.op).stateful_rng
            for n in sym._active_nodes() if not n.is_var())

    @property
    def symbol(self):
        return self._sym

    def _obs_name(self):
        outs = self._sym.list_outputs()
        return outs[0] if outs else "cached_op"

    # ------------------------------------------------------------------
    def _get_fn(self, is_train, diff_names):
        from . import inspector as _inspector
        from .ops.nn import residual_knobs
        # keyed on the NaN-guard flag so toggling set_nan_guard()
        # retraces with/without the staged checks; ditto the residual-
        # format env knobs (int8/bn/relu/pool), which are read at trace
        # time
        key = (is_train, diff_names, _inspector.nan_guard_enabled(),
               mirror_enabled(self._flags) if diff_names else False,
               residual_knobs())
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        if _obs.enabled() and self._fns:
            # a second+ python-level variant of this op — legitimate
            # when a toggle (train/diff-set/guard) flipped, but the
            # detector records it so a variant storm is visible
            _obs_recompile.record_retrace(
                "CachedOp[%s]" % self._obs_name(),
                "train=%s diff=%d guard=%s mirror=%s"
                % (key[0], len(key[1]), key[2], key[3]))
        graph_fn = build_graph_fn(self._sym, is_train=is_train)

        if diff_names:
            def pure(diff_list, rest, aux, rng_key):
                full = dict(rest)
                full.update(zip(diff_names, diff_list))
                outs, aux_up = graph_fn(full, aux, rng_key)
                return tuple(outs), aux_up
            # hybridize(backward_do_mirror=True) / MXNET_BACKWARD_DO_MIRROR:
            # remat the traced graph so backward recomputes activations
            # under the mirror policy instead of storing them
            pure = apply_mirror(pure, mirror_enabled(self._flags))

            def fwd_res(diff_list, rest, aux, rng_key):
                # compile forward + residuals ONCE per signature; the
                # vjp closure is a jax.tree_util.Partial and crosses the
                # jit boundary (executor.fwd_res_fn does the same) — a
                # per-call jax.vjp would re-trace the whole graph
                outs_aux, vjp = jax.vjp(
                    lambda d: pure(d, rest, aux, rng_key), diff_list,
                    has_aux=False)
                return outs_aux, vjp
            fn = jax.jit(fwd_res)
        else:
            def pure(args, aux, rng_key):
                outs, aux_up = graph_fn(args, aux, rng_key)
                return tuple(outs), aux_up
            fn = jax.jit(pure)
        self._fns[key] = fn
        return fn

    # ------------------------------------------------------------------
    def __call__(self, *inputs):
        from . import ndarray as nd

        if len(inputs) != len(self._input_names):
            raise MXNetError(
                "CachedOp expects %d inputs (%s), got %d"
                % (len(self._input_names), self._input_names, len(inputs)))
        by_name = dict(zip(self._input_names, inputs))
        args = {n: by_name[n]._data for n in self._arg_names}
        aux = {n: by_name[n]._data for n in self._aux_names}
        rng_key = _random.next_key() if self._needs_rng else _zero_key()
        is_train = autograd.is_training()
        recording = autograd.is_recording()

        diff_names = tuple(
            n for n in self._arg_names
            if recording and by_name[n]._requires_tape())

        sig = None
        if _obs.enabled():
            # jit-boundary breadcrumb: if XLA re-traces inside the call
            # below, the detector attributes it to this signature
            sig = _obs_recompile.signature_of(
                inputs, train=is_train, diff=len(diff_names))
            _obs_recompile.note_call(
                "CachedOp[%s]" % self._obs_name(), sig)

        ctx = inputs[0]._ctx if inputs else None

        if diff_names:
            fn = self._get_fn(is_train, diff_names)
            diff_list = [args[n] for n in diff_names]
            if _membudget.enabled():
                _membudget.preflight(
                    "CachedOp[%s].fwd" % self._obs_name(), fn,
                    (diff_list, args, aux, rng_key), signature=sig)
            try:
                (outs, aux_up), vjp_fn = fn(diff_list, args, aux,
                                            rng_key)
            except Exception as exc:
                _membudget.note_oom(
                    "CachedOp[%s].fwd" % self._obs_name(), exc)
                raise

            diff_nds = [by_name[n] for n in diff_names]

            def tape_vjp(cts):
                cts_t = tuple(cts) if isinstance(cts, (tuple, list)) \
                    else (cts,)
                # cotangent structure matches pure's (outs, aux_up); aux
                # updates get zero cotangents. Apply the vjp closure
                # INSIDE jit (it is a Partial — a pytree of residuals):
                # calling it bare would interpret the backward jaxpr
                # op-by-op eagerly — no XLA fusion, and on the CPU mesh
                # the resulting flock of in-flight collective launches
                # deadlocks (engine.py). Executor.bwd_fn does the same.
                aux_ct = jax.tree.map(jnp.zeros_like, aux_up)
                origin = "CachedOp[%s].step" % self._obs_name()
                if sig is not None and _obs_attr.ops_enabled() \
                        and _obs_attr.needs_program(origin, sig):
                    # per-operator attribution: register a combined
                    # fwd+vjp analysis program. The runtime executes
                    # fn and _apply_vjp as two programs, but replaying
                    # the stored vjp closure in a separate jit drops
                    # the op_name name-stack metadata — re-deriving the
                    # vjp inside ONE traced program keeps every
                    # backward instruction attributed to its block.
                    def _step(diff, rest, aux_a, key, ct):
                        _o, v = fn(diff, rest, aux_a, key)
                        return _o, _apply_vjp(v, ct)
                    _obs_attr.register_program(
                        origin, sig, jax.jit(_step),
                        (diff_list, args, aux, rng_key,
                         (cts_t, aux_ct)))
                if _membudget.enabled():
                    _membudget.preflight(origin, signature=sig)
                try:
                    grads = _apply_vjp(vjp_fn, (cts_t, aux_ct))
                except Exception as exc:
                    _membudget.note_oom(origin, exc)
                    raise
                return grads

            node = autograd.TapeNode(
                tape_vjp, diff_nds, len(outs),
                [tuple(o.shape) for o in outs], [o.dtype for o in outs],
                op_name="CachedOp")
            autograd._record_node(node)
            results = []
            for k, o in enumerate(outs):
                r = nd.NDArray(o, ctx)
                r._ag_node = (node, k)
                results.append(r)
        else:
            fn = self._get_fn(is_train, ())
            if sig is not None and _obs_attr.ops_enabled():
                _obs_attr.register_program(
                    "CachedOp[%s].fwd" % self._obs_name(), sig, fn,
                    (args, aux, rng_key))
            if _membudget.enabled():
                _membudget.preflight(
                    "CachedOp[%s].fwd" % self._obs_name(), fn,
                    (args, aux, rng_key), signature=sig)
            try:
                outs, aux_up = fn(args, aux, rng_key)
            except Exception as exc:
                _membudget.note_oom(
                    "CachedOp[%s].fwd" % self._obs_name(), exc)
                raise
            results = [nd.NDArray(o, ctx) for o in outs]

        for name, val in aux_up.items():
            by_name[name]._data = val

        datas = [r._data for r in results]
        _engine.sync_if_needed(datas)

        return results
