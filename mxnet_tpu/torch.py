"""PyTorch tensor interop (reference: python/mxnet/torch.py).

The reference bridged to Lua-torch kernels; that runtime is gone. The
useful modern capability under the same module name is tensor exchange
with PyTorch through DLPack — zero-copy on shared-memory backends."""

from . import ndarray as nd

__all__ = ["to_torch", "from_torch"]


def to_torch(array):
    """NDArray -> torch.Tensor via the DLPack protocol."""
    import torch as _torch
    return _torch.from_dlpack(nd.to_dlpack_for_read(array))


def from_torch(tensor):
    """torch.Tensor -> NDArray via the DLPack protocol."""
    return nd.from_dlpack(tensor)
