"""Shared plumbing for the Pallas kernel families.

kernels/flash_attention.py (training flash + per-sequence decode) and
kernels/paged_decode.py (the batched-lane paged decode/verify kernel)
need the same four things: the masking value, the lane-padded stat
layout, the block clamp/divisibility rule, and a per-shape block_k
choice cache. They live here so neither family copies the other —
a fix to the mask or the block rule lands in both kernels at once.
"""

import math
import os
import warnings

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Row statistics (m, l, lse, delta, amax) ride through HBM/VMEM with a
# trailing lane dimension, every lane holding the same value. Mosaic
# requires the last two dims of any block to be (8, 128)-divisible or
# equal to the array dims; a [rows]-shaped stat with the batch dim
# squeezed out of the block violates that, so [rows, 128] is the
# lowerable layout (same choice as jax's reference TPU kernels). The
# rule's "equal to the array dim" clause also admits [rows, 1] blocks
# at 1/128th the stat HBM traffic (the dk/dv kernel re-streams lse and
# delta once per q block) — env-overridable for the on-chip A/B
# (benchmark/run_chip_queue.py flash_stat_lanes1 / train_lm_lanes1).
STAT_LANES = int(os.environ.get("MXNET_FLASH_STAT_LANES", "128"))

MIN_BLOCK = 8           # below this the grid is degenerate, not tiled


def causal_mask(s, q_start, k_start, block_q, block_k):
    """Mask score block s [block_q, block_k] to the causal triangle:
    global query row q_start+i may attend global key k_start+j only
    when q_pos >= k_pos."""
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def length_mask(s, k_start, limits):
    """Mask key positions at/past each row's valid length: s
    [rows, block_k] scores for global key positions starting at
    k_start; ``limits`` is a [rows, 1] (or scalar) EXCLUSIVE bound —
    row r attends k_pos < limits[r]. The decode kernels' dynamic-
    length mask (one compiled program serves every position)."""
    rows, block_k = s.shape
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (rows, block_k), 1)
    return jnp.where(k_pos < limits, s, NEG_INF)


def adjust_block(block, seq, name, family="flash_attention"):
    """Clamp ``block`` to ``seq`` and make it divide; refuse to let the
    gcd collapse toward 1 (prime/odd T with a non-dividing block) —
    that is a correct but pathologically fine grid of near-one-element
    steps. Fall back to ONE full-sequence block and warn so an explicit
    or env block choice that does not divide T is visible (ADVICE r5:
    previously a silent degenerate grid)."""
    adjusted = min(block, seq)
    if seq % adjusted:
        adjusted = math.gcd(seq, adjusted)
    if adjusted < min(seq, MIN_BLOCK):
        warnings.warn(
            "%s: %s=%d does not divide sequence length %d "
            "and the gcd adjustment collapses to %d (a degenerate "
            "%d-step grid); falling back to a single full-sequence "
            "block of %d. Pick a %s that divides the sequence to tile "
            "properly." % (family, name, block, seq, adjusted,
                           seq // max(adjusted, 1), seq, name),
            stacklevel=3)
        return seq
    return adjusted


# ------------------------------------------- per-shape block_k cache ---
# Both decode kernel families pick block_k the same way: largest
# preferred tile that divides the cache length (falling back to one
# full-length block). The choice is pure shape math, but it sat on the
# per-call path of flash_decode_with_lse (recomputed every call) and
# the paged kernel adds an env override + a pool-block multiple
# constraint — so the choice is computed once per distinct shape key
# and memoized process-wide. The cache is tiny (a handful of serving
# shapes per process) and never evicts.

_BLOCK_CHOICE = {}


def choose_block_k(t_max, shape_key=(), candidates=(512, 256, 128),
                   multiple=1, env=None):
    """The cached block_k for a cache of length ``t_max``.

    ``shape_key`` distinguishes callers/shapes that would otherwise
    collide (kernel family, batch, heads, head_dim, dtype...).
    ``candidates`` are tried in order; the first that divides t_max and
    is a multiple of ``multiple`` (the paged pool's block size — a
    grid step stages whole pool blocks) wins, else ONE full-length
    block. ``env`` names an env var holding an explicit override,
    validated against the same constraints (invalid values warn and
    fall back rather than building an untileable grid)."""
    key = (env, int(t_max), int(multiple)) + tuple(shape_key)
    hit = _BLOCK_CHOICE.get(key)
    if hit is not None:
        return hit
    choice = None
    if env:
        raw = os.environ.get(env)
        if raw:
            try:
                val = int(raw)
            except ValueError:
                val = -1
            if val > 0 and val % multiple == 0 and t_max % val == 0:
                choice = val
            else:
                warnings.warn(
                    "%s=%r is not a positive multiple of %d dividing "
                    "cache length %d; using the default block choice"
                    % (env, raw, multiple, t_max), stacklevel=2)
    if choice is None and env == "MXNET_PAGED_BLOCK_K" \
            and os.environ.get("MXNET_OBS_PROFILE_DIR"):
        # an ARCHIVED winner beats the static heuristic: the profile
        # store holds measured p50s per MXNET_PAGED_BLOCK_K config
        # fingerprint from past A/B runs (ISSUE 18 / ROADMAP item 5's
        # predict-and-prune). Only for callers keyed on that knob —
        # flash_decode doesn't honor it, so a paged winner must not
        # leak into its grid. One guarded branch — with the store
        # unset this is a single env read; the memo above means the
        # archive is consulted once per distinct shape key.
        try:
            from ..observability import costmodel
            choice = costmodel.archived_block_k(t_max,
                                                multiple=multiple)
        except Exception:
            choice = None
    if choice is None:
        choice = next((bb for bb in candidates
                       if bb % multiple == 0 and t_max % bb == 0),
                      t_max)
    choice = min(choice, t_max)
    _BLOCK_CHOICE[key] = choice
    return choice


def block_choice_cache():
    """Snapshot of the memoized choices (tests / diagnostics)."""
    return dict(_BLOCK_CHOICE)
