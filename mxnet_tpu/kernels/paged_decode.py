"""Serving-native paged decode/verify attention — ONE batched-lane
Pallas kernel family for the paged x int8 x GQA x spec-verify layout.

The serving stack's hot read (`models/transformer.py
decode_step_paged` / `verify_chunk_paged`) lowers today as a fused XLA
gather that MATERIALIZES the dense [B, T, KVH, D] cache view and feeds
it to a dense contraction: every decode step moves ~3x the live cache
bytes (read pool + write copy + read copy), pays the full table
capacity T for every lane regardless of its live length, and under
int8-KV dequantizes nothing early only because the contraction is
int8 — the copy itself is still the tax. The per-sequence flash-decode
kernel is not the answer either: a [1, T] score read gives flash
scheduling nothing to skip, and the chip A/B retired it at 841 tok/s
vs 4075 dense (PERF.md round 5).

This kernel serves the real layout directly, one grid for the whole
batch:

  * block-table gathers INSIDE the grid — BlockSpec index maps read
    the scalar-prefetched tables, so pool blocks stream HBM->VMEM
    exactly once per (lane, KV head) with no dense copy in between;
  * dead steps skipped — a lane whose live length ends before a grid
    step redirects that step's DMA to the null block and skips the
    compute, so a short lane costs its LIVE length, not the table
    capacity (the adaptivity "keyed on max live length" is dynamic,
    per lane, inside one compiled program);
  * GQA head-packing — the G query heads sharing a KV head ride one
    [C*G, D] MXU contraction, reading each cache block once per group;
  * int8-KV fused dequant — codes stay int8 into the MXU (int8 x int8
    -> int32), per-block k-scales multiply scores AFTER the
    contraction and v-scales fold into the re-quantized probabilities,
    replicating `_int8_cache_attention`'s op order exactly;
  * the ragged [B, k+1] spec-verify window is the span>1 case of the
    SAME kernel: packed row r = c*G + g masks key positions
    <= pos[b] + c, which at span=1 is plain decode.

Numerics contract: pass-for-pass the score/scale/mask op order of the
dense reference paths, so greedy token streams are identical (tested
in tests/test_paged_kernel.py; residual diffs are reduction-order
ulps — int32 score/PV accumulation is exactly associative, the fp
softmax statistics carry ~1e-7 sum-order noise). To hold the int8 and
bf16 prob-quantization order (the references quantize NORMALIZED
probabilities), the kernel is TWO-PASS over the same grid — a stats
trip (m, l, amax) then a PV trip re-streaming K/V — rather than
single-pass online softmax; the second K read is the price of
bit-faithful code emission.

block_k (pool blocks staged per grid step) adapts per shape through
kernels/common.choose_block_k's process-wide cache, override
MXNET_PAGED_BLOCK_K. Wired behind MXNET_PAGED_DECODE_PALLAS=1 in
models/transformer.py; the batcher's membudget preflight covers the
jit boundary it rides in, and the attribution scopes
`paged_decode_kernel` / `paged_verify_kernel` carry its bytes.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import NEG_INF, STAT_LANES, choose_block_k

__all__ = ["paged_attention"]


def _paged_kernel(tables_ref, pos_ref, q_ref, *refs, kb, bs, num_kb,
                  span, g, int8):
    """Grid (B, KVH, 2, num_kb); trip p=0 accumulates the softmax
    statistics, trip p=1 re-reads K/V and accumulates PV. Scratch
    persists across the sequential (p, ki) axes of one (b, h)."""
    if int8:
        k_refs = refs[0:kb]
        v_refs = refs[kb:2 * kb]
        ks_refs = refs[2 * kb:3 * kb]
        vs_refs = refs[3 * kb:4 * kb]
        o_ref, acc_sc, m_sc, l_sc, amax_sc = refs[4 * kb:]
    else:
        k_refs = refs[0:kb]
        v_refs = refs[kb:2 * kb]
        o_ref, acc_sc, m_sc, l_sc = refs[2 * kb:]
        amax_sc = None
    b = pl.program_id(0)
    h = pl.program_id(1)
    p = pl.program_id(2)
    ki = pl.program_id(3)
    rows, d = q_ref.shape
    block_k = kb * bs
    pos = pos_ref[b]
    k_start = ki * block_k
    # the last key position any row of this lane may attend; steps
    # past it are dead (their DMAs were redirected to the null block
    # by the index maps — see _pool_index in paged_attention)
    live = k_start <= pos + span - 1

    @pl.when(jnp.logical_and(p == 0, ki == 0))
    def _init_stats():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        if int8:
            amax_sc[...] = jnp.zeros_like(amax_sc)

    @pl.when(jnp.logical_and(p == 1, ki == 0))
    def _init_acc():
        acc_sc[...] = jnp.zeros_like(acc_sc)

    def _head_plane(scale_refs):
        """Stage this step's per-(position, head) scale planes and
        select head h's column: [block_k]. Rank-1 dynamic indexing is
        not Mosaic-lowerable, so the selection is a one-hot
        multiply-sum (exact: one nonzero term)."""
        cat = jnp.concatenate([r[...] for r in scale_refs], axis=0)
        kvh = cat.shape[1]
        sel = jax.lax.broadcasted_iota(jnp.int32,
                                       (block_k, kvh), 1) == h
        return jnp.sum(jnp.where(sel, cat, 0.0), axis=1)

    def _scores():
        """[rows, block_k] masked scores, replicating the dense
        reference op order exactly (scores are recomputed identically
        on both trips — int32 dots make them bit-stable)."""
        k = jnp.concatenate([r[...] for r in k_refs], axis=0)
        if int8:
            # _kv_quant(q) per call, like _int8_cache_attention
            qf = q_ref[...].astype(jnp.float32)
            qs = jnp.maximum(jnp.max(jnp.abs(qf), axis=-1),
                             1e-8) / 127.0
            q8 = jnp.clip(jnp.round(qf / qs[:, None]),
                          -127, 127).astype(jnp.int8)
            s = jax.lax.dot_general(
                q8, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32).astype(jnp.float32)
            s = s * qs[:, None] * _head_plane(ks_refs)[None, :] \
                / np.sqrt(d)
        else:
            s = jax.lax.dot_general(
                q_ref[...], k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) / np.sqrt(d)
        # packed row r = c*G + g attends key positions <= pos + c
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), 1)
        c_row = jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), 0) // g
        return jnp.where(k_pos <= pos + c_row, s, NEG_INF)

    @pl.when(jnp.logical_and(live, p == 0))
    def _stats_step():
        s = _scores()
        m_prev = m_sc[...]                   # [rows, LANES], lanes equal
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new[:, :1])     # masked entries underflow to 0
        l_sc[...] = alpha * l_sc[...] + pexp.sum(axis=1, keepdims=True)
        if int8:
            av = pexp * _head_plane(vs_refs)[None, :]
            amax_sc[...] = jnp.maximum(amax_sc[...] * alpha,
                                       av.max(axis=1, keepdims=True))
        m_sc[...] = m_new

    @pl.when(jnp.logical_and(live, p == 1))
    def _pv_step():
        s = _scores()
        m = m_sc[...][:, :1]
        l = l_sc[...][:, :1]
        a = jnp.exp(s - m) / l               # normalized, like the refs
        v = jnp.concatenate([r[...] for r in v_refs], axis=0)
        if int8:
            # _kv_quant(a * vs) with the row-global scale from pass 0
            as_ = jnp.maximum(amax_sc[...][:, :1] / l, 1e-8) / 127.0
            a8 = jnp.clip(jnp.round(a * _head_plane(vs_refs)[None, :]
                                    / as_), -127, 127).astype(jnp.int8)
            acc_sc[...] += jax.lax.dot_general(
                a8, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
        else:
            acc_sc[...] += jax.lax.dot_general(
                a.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(p == 1, ki == num_kb - 1))
    def _flush():
        if int8:
            l = l_sc[...][:, :1]
            as_ = jnp.maximum(amax_sc[...][:, :1] / l, 1e-8) / 127.0
            o_ref[...] = (acc_sc[...].astype(jnp.float32)
                          * as_).astype(o_ref.dtype)
        else:
            o_ref[...] = acc_sc[...].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("kb", "bs", "num_kb",
                                             "span", "g", "interpret"))
def _paged_call(q, kpool, vpool, ks, vs, tables, pos, kb, bs, num_kb,
                span, g, interpret):
    """q packed [B, KVH, span*G, D]; pools [NB, bs, KVH, D] (+ scale
    planes [NB, bs, KVH]); tables [B, num_kb*kb]; pos [B]. Returns
    o [B, KVH, span*G, D] in q.dtype."""
    int8 = ks is not None
    b, kvh, rows, d = q.shape
    block_k = kb * bs

    def _scalar_args(idx):
        return idx[:4], idx[4], idx[5]       # grid ids, tables, pos

    def _pool_index(i):
        # table entry for pool block i of grid step ki; dead steps
        # (whole step past the lane's deepest attendable position)
        # redirect to the reserved null block 0 — the DMA is cheap,
        # repeated, and never read (compute is pl.when-skipped)
        def idx(b_, h_, p_, ki_, tables_ref, pos_ref):
            blk = tables_ref[b_, ki_ * kb + i]
            live = ki_ * block_k <= pos_ref[b_] + span - 1
            return (jnp.where(live, blk, 0), 0, h_, 0)
        return idx

    def _scale_index(i):
        def idx(b_, h_, p_, ki_, tables_ref, pos_ref):
            blk = tables_ref[b_, ki_ * kb + i]
            live = ki_ * block_k <= pos_ref[b_] + span - 1
            return (jnp.where(live, blk, 0), 0, 0)
        return idx

    def _q_index(b_, h_, p_, ki_, tables_ref, pos_ref):
        return (b_, h_, 0, 0)

    qspec = pl.BlockSpec((None, None, rows, d), _q_index)
    kvspec = [pl.BlockSpec((None, bs, None, d), _pool_index(i))
              for i in range(kb)]
    in_specs = [qspec] + kvspec + kvspec
    inputs = [q] + [kpool] * kb + [vpool] * kb
    scratch = [
        pltpu.VMEM((rows, d), jnp.int32 if int8 else jnp.float32),
        pltpu.VMEM((rows, STAT_LANES), jnp.float32),
        pltpu.VMEM((rows, STAT_LANES), jnp.float32),
    ]
    if int8:
        sspec = [pl.BlockSpec((None, bs, kvh), _scale_index(i))
                 for i in range(kb)]
        in_specs += sspec + sspec
        inputs += [ks] * kb + [vs] * kb
        scratch.append(pltpu.VMEM((rows, STAT_LANES), jnp.float32))

    kernel = functools.partial(_paged_kernel, kb=kb, bs=bs,
                               num_kb=num_kb, span=span, g=g, int8=int8)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, 2, num_kb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, rows, d), _q_index),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, rows, d), q.dtype),
        interpret=interpret,
    )(tables, pos, *inputs)


def paged_attention(q, layer_pool, tables, pos, block_k=None,
                    interpret=None):
    """Batched-lane attention straight against one layer's block pool.

    q: [B, C, H, D] — C=1 is plain decode, C=k+1 the spec-verify
    window (row (b, c) holds the query at stream position pos[b]+c).
    layer_pool: {"k", "v": [NB, bs, KVH, D]} plus {"ks", "vs":
    [NB, bs, KVH] fp32} under int8-KV (detected by key presence, like
    every pool consumer).
    tables: [B, max_len // bs] int32 block tables (entry j covers
    positions [j*bs, (j+1)*bs); unallocated entries = null block 0).
    pos: [B] int32 — row (b, c) attends pool positions <= pos[b] + c,
    exactly the dense reference masks. The window's own K/V must
    already be written to the pool (the transformer wiring writes
    before it reads, so causal-within-window is implied by position).

    Returns [B, C, H, D] in q.dtype, matching `_decode_attention` /
    `verify_chunk_paged`'s contraction up to reduction-order ulps
    (greedy-stream identical; see the module docstring).

    block_k=None resolves through kernels/common.choose_block_k
    (largest of 512/256/128 that both divides the table capacity and
    is a multiple of the pool block size, else one full-capacity
    step; MXNET_PAGED_BLOCK_K overrides) — memoized per shape.
    `interpret` defaults to True off TPU so the same code runs
    everywhere (tier-1 parity tests run it on CPU).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    int8 = "ks" in layer_pool
    kpool, vpool = layer_pool["k"], layer_pool["v"]
    nblocks, bs, kvh, d = kpool.shape
    b, span, h, dq = q.shape
    if h % kvh:
        raise ValueError("query heads %d must be a multiple of KV "
                         "heads %d" % (h, kvh))
    g = h // kvh
    rows = span * g
    nb = int(tables.shape[1])
    t_max = nb * bs
    if block_k is None:
        block_k = choose_block_k(
            t_max, shape_key=("paged", b, kvh, rows, d,
                              str(jnp.dtype(kpool.dtype)), bs),
            multiple=bs, env="MXNET_PAGED_BLOCK_K")
    block_k = min(block_k, t_max)
    if block_k % bs or t_max % block_k:
        raise ValueError(
            "block_k %d must be a multiple of the pool block size %d "
            "and divide the table capacity %d" % (block_k, bs, t_max))
    kb = block_k // bs
    num_kb = nb // kb
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    tables = jnp.asarray(tables, jnp.int32)
    # GQA head-packing: [B, C, H, D] -> [B, KVH, C*G, D]; packed row
    # r = c*G + g_idx, so r // G recovers the window offset c
    qp = q.reshape(b, span, kvh, g, d).transpose(0, 2, 1, 3, 4) \
         .reshape(b, kvh, rows, d)
    o = _paged_call(qp, kpool, vpool,
                    layer_pool.get("ks"), layer_pool.get("vs"),
                    tables, pos, kb, bs, num_kb, span, g, interpret)
    return o.reshape(b, kvh, span, g, d).transpose(0, 2, 1, 3, 4) \
            .reshape(b, span, h, d)
