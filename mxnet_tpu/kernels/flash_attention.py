"""Blocked (flash) attention as a Pallas TPU kernel — streamed K/V and
a custom flash backward.

Softmax(QK^T)V without materialising the [Tq, Tk] score matrix in HBM.
Forward: grid (batch*heads, q_blocks, k_blocks); each step stages one
[block_q, D] query block and one [block_k, D] key/value block into VMEM
through BlockSpec index maps (K/V live in HBM and STREAM block by block
— nothing holds the full sequence in VMEM, so sequence length is bounded
by HBM, not VMEM). The online-softmax accumulator (o, m, l) lives in
VMEM scratch and is carried across the k axis, which is the innermost,
sequential ("arbitrary") grid dimension.

Backward: the standard flash decomposition with recompute —
  delta = rowsum(dO * O)                      (jnp, fused by XLA)
  dQ kernel: grid (bh, q_blocks, k_blocks), accumulates over k
  dK/dV kernel: grid (bh, k_blocks, q_blocks), accumulates over q
using the saved per-row logsumexp instead of the (m, l) pair, so only
per-row statistics are saved — activation memory is O(T * _STAT_LANES)
(the lane-padded stat layout below), not O(T^2).

This is the dense per-device block compute under parallel/ring.py's
sequence-parallel ring; reference counterpart: the fused attention in
src/operator/contrib/transformer.cu (MXNet's interleaved_matmul_*
ops), re-thought for the MXU/VMEM hierarchy instead of warp shuffles.
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# masking value, stat-lane layout, block clamp rule, and the per-shape
# block_k choice cache are shared with kernels/paged_decode.py — one
# source of truth for both kernel families (kernels/common.py)
from .common import (NEG_INF as _NEG_INF, STAT_LANES as _STAT_LANES,
                     causal_mask as _causal_mask, choose_block_k)
from .common import adjust_block as _adjust_block_common


# ------------------------------------------------------------- forward --
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc,
                *, causal, scale, num_kb):
    block_q, head_dim = q_ref.shape
    block_k = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    q_start = qi * block_q
    k_start = ki * block_k
    # blocks strictly above the causal diagonal contribute nothing
    live = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[...].astype(jnp.float32) * scale
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, k_start, block_q, block_k)
        m_prev = m_sc[...]                       # [bq, LANES], lanes equal
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        l_sc[...] = alpha * l_sc[...] + p.sum(axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_sc[...] = alpha[:, :1] * acc_sc[...] + pv
        m_sc[...] = m_new

    @pl.when(ki == num_kb - 1)
    def _flush():
        l = jnp.maximum(l_sc[...], 1e-30)
        o_ref[...] = (acc_sc[...] / l[:, :1]).astype(o_ref.dtype)
        lse_ref[...] = m_sc[...] + jnp.log(l)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret"))
def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    """q: [BH, Tq, D], k/v: [BH, Tk, D] ->
    (o [BH, Tq, D], lse [BH, Tq, _STAT_LANES] — lanes all equal)."""
    bh, seq_q, head_dim = q.shape
    seq_k = k.shape[1]
    scale = 1.0 / (head_dim ** 0.5)
    num_kb = seq_k // block_k
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               num_kb=num_kb)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, seq_q // block_q, num_kb),
        in_specs=[
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((None, block_q, _STAT_LANES),
                         lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            # (o, m, l) online-softmax carry, persistent across the
            # sequential k axis
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ----------------------------------------------- ring-carry variant -----
def _carry_kernel(off_ref, q_ref, k_ref, v_ref, oi_ref, mi_ref, li_ref,
                  o_ref, m_ref, l_ref, acc_sc, m_sc, l_sc,
                  *, causal, scale, num_kb):
    """One ring step: fold this device's current K/V shard into the
    (o, m, l) online-softmax carry. Offsets of the q and kv shards in
    the GLOBAL sequence arrive as scalars (SMEM) because they depend on
    the traced ring position."""
    block_q, head_dim = q_ref.shape
    block_k = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    q_off = off_ref[0]
    kv_off = off_ref[1]

    @pl.when(ki == 0)
    def _load_carry():
        acc_sc[...] = oi_ref[...].astype(jnp.float32)
        m_sc[...] = mi_ref[...].astype(jnp.float32)
        l_sc[...] = li_ref[...].astype(jnp.float32)

    q_start = q_off + qi * block_q
    k_start = kv_off + ki * block_k
    live = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[...].astype(jnp.float32) * scale
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, k_start, block_q, block_k)
        m_prev = m_sc[...]                       # [bq, LANES], lanes equal
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        l_sc[...] = alpha * l_sc[...] + p.sum(axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_sc[...] = alpha[:, :1] * acc_sc[...] + pv
        m_sc[...] = m_new

    @pl.when(ki == num_kb - 1)
    def _flush():
        o_ref[...] = acc_sc[...]
        m_ref[...] = m_sc[...]
        l_ref[...] = l_sc[...]


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "vma"))
def flash_carry_block(q, k, v, o, m, l, q_offset, kv_offset, causal,
                      block_q=128, block_k=128, interpret=None,
                      vma=None):
    """UNNORMALIZED flash update for ring attention: q [BH, Tq, D],
    k/v [BH, Tk, D], carry o [BH, Tq, D] (f32), m/l [BH, Tq] (f32);
    offsets are traced int32 scalars (global positions of element 0).
    Returns the updated (o, m, l). The caller normalizes o / l at the
    end of the ring (parallel/ring.py)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bh, seq_q, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            "ring shard lengths (%d, %d) must divide by blocks (%d, %d)"
            % (seq_q, seq_k, block_q, block_k))
    scale = 1.0 / (head_dim ** 0.5)
    num_kb = seq_k // block_k
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(kv_offset, jnp.int32)])

    def _struct(shape):
        # under a partially-manual shard_map the checker needs to know
        # which mesh axes the kernel outputs vary over (vma)
        if vma:
            try:
                return jax.ShapeDtypeStruct(shape, jnp.float32,
                                            vma=frozenset(vma))
            except TypeError:
                pass
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    kernel = functools.partial(_carry_kernel, causal=causal, scale=scale,
                               num_kb=num_kb)
    grid = (bh, seq_q // block_q, num_kb)
    qspec = pl.BlockSpec((None, block_q, head_dim),
                         lambda b, qi, ki: (b, qi, 0))
    kspec = pl.BlockSpec((None, block_k, head_dim),
                         lambda b, qi, ki: (b, ki, 0))
    rspec = pl.BlockSpec((None, block_q, _STAT_LANES),
                         lambda b, qi, ki: (b, qi, 0))
    stat3 = (bh, seq_q, _STAT_LANES)
    o, m3, l3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # offsets, whole
            qspec, kspec, kspec, qspec, rspec, rspec,
        ],
        out_specs=[qspec, rspec, rspec],
        out_shape=[_struct(o.shape), _struct(stat3), _struct(stat3)],
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(offsets, q, k, v, o,
      jnp.broadcast_to(m[:, :, None], stat3),
      jnp.broadcast_to(l[:, :, None], stat3))
    return o, m3[..., 0], l3[..., 0]


# ------------------------------------------------------------ backward --
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_sc, *, causal, scale, num_kb):
    block_q, head_dim = q_ref.shape
    block_k = k_ref.shape[0]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    q_start = qi * block_q
    k_start = ki * block_k
    live = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[...].astype(jnp.float32) * scale
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, k_start, block_q, block_k)
        p = jnp.exp(s - lse_ref[...][:, :1])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[...][:, :1])
        dq_sc[...] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_kb - 1)
    def _flush():
        dq_ref[...] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, causal, scale, num_qb):
    block_k, head_dim = k_ref.shape
    block_q = q_ref.shape[0]
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    q_start = qi * block_q
    k_start = ki * block_k
    # for this k block, q blocks that end before the diagonal are dead
    live = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[...].astype(jnp.float32) * scale
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, q_start, k_start, block_q, block_k)
        p = jnp.exp(s - lse_ref[...][:, :1])           # [bq, bk]
        dv_sc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[...][:, :1])          # [bq, bk]
        # q is already scaled by 1/sqrt(D) above, which supplies the
        # single scale factor of dK = scale * dS^T Q
        dk_sc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _flush():
        dk_ref[...] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_sc[...].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret"))
def _flash_bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret):
    bh, seq_q, head_dim = q.shape
    seq_k = k.shape[1]
    scale = 1.0 / (head_dim ** 0.5)
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    # delta_i = sum_d dO_i O_i — tiny elementwise+reduce, XLA fuses it;
    # broadcast into the stat-lane layout the kernels stream (lse
    # already arrives in it from the forward)
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1, keepdims=True), lse.shape)

    sspec_q = pl.BlockSpec((None, block_q, _STAT_LANES),
                           lambda b, qi, ki: (b, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale,
                          num_kb=num_kb),
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, qi, ki: (b, qi, 0)),
            sspec_q, sspec_q,
        ],
        out_specs=pl.BlockSpec((None, block_q, head_dim),
                               lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale,
                          num_qb=num_qb),
        grid=(bh, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((None, block_q, _STAT_LANES),
                         lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((None, block_q, _STAT_LANES),
                         lambda b, ki, qi: (b, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------- custom vjp ---
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bh(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_bh_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bh_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, causal, block_q,
                            block_k, interpret)
    return dq, dk, dv


_flash_bh.defvjp(_flash_bh_fwd, _flash_bh_bwd)


# ------------------------------------------------------------- decode ---
def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc,
                   m_sc, l_sc, *, scale, block_k, num_kb):
    """T_q=1 step: the query rows of one KV head (1 for MHA, the G
    grouped heads for GQA) attend to that head's cache, streamed block
    by block. The valid cache length arrives per row through SMEM; key
    positions at or past it are masked out of the online softmax, so
    one compiled kernel serves every decode position. With GQA the
    cache block is read ONCE for all G query rows — the HBM saving is
    the point of grouping."""
    b = pl.program_id(0)
    ki = pl.program_id(1)
    length = len_ref[b]
    g = q_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    k_start = ki * block_k

    @pl.when(k_start < length)
    def _step():
        q = q_ref[...].astype(jnp.float32) * scale       # (G, D)
        k = k_ref[...].astype(jnp.float32)               # (block_k, D)
        v = v_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                                   (g, block_k), 1)
        s = jnp.where(k_pos < length, s, _NEG_INF)
        m_prev = m_sc[...]                       # [g, LANES], lanes equal
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        l_sc[...] = alpha * l_sc[...] + p.sum(axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_sc[...] = alpha[:, :1] * acc_sc[...] + pv
        m_sc[...] = m_new

    @pl.when(ki == num_kb - 1)
    def _flush():
        l = jnp.maximum(l_sc[...], 1e-30)
        o_ref[...] = (acc_sc[...] / l[:, :1]).astype(o_ref.dtype)
        # lse = m + log(l): log of the true sum of exp(scores) over this
        # cache — the sufficient statistic for cross-shard combination
        # (sequence-parallel flash decoding); rows with no valid keys
        # flush to ~-inf and drop out of the combine
        lse_ref[...] = m_sc[...] + jnp.log(l)


@functools.partial(jax.jit,
                   static_argnames=("block_k", "interpret"))
def _flash_decode_bh(q, k, v, lengths, block_k, interpret):
    """q [BKV, G, D] (G query rows share each KV row — 1 for MHA, the
    group size for GQA), k/v [BKV, Tmax, D], lengths [BKV] ->
    (o [BKV, G, D], lse [BKV, G, _STAT_LANES] — lanes all equal)."""
    bkv, t_max, head_dim = k.shape
    g = q.shape[1]
    scale = 1.0 / (head_dim ** 0.5)
    num_kb = t_max // block_k
    kernel = functools.partial(_decode_kernel, scale=scale,
                               block_k=block_k, num_kb=num_kb)
    return pl.pallas_call(
        kernel,
        grid=(bkv, num_kb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((None, g, head_dim), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((None, block_k, head_dim),
                         lambda b, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, g, head_dim), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((None, g, _STAT_LANES), lambda b, ki: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkv, g, head_dim), q.dtype),
            jax.ShapeDtypeStruct((bkv, g, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, head_dim), jnp.float32),
            pltpu.VMEM((g, _STAT_LANES), jnp.float32),
            pltpu.VMEM((g, _STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(lengths, q, k, v)


def flash_decode(q, k_cache, v_cache, lengths, block_k=None,
                 interpret=None):
    """Single-step (T_q=1) attention against a KV cache.

    q: [B, H, D] — the current token's queries.
    k_cache/v_cache: [B, Tmax, KVH, D] — preallocated cache (KVH = H
    for MHA; any divisor of H for GQA, where each cache block is read
    once per query GROUP); only the first `lengths` positions of each
    row are attended.
    lengths: int32 [B] (or scalar, broadcast) valid cache lengths.

    Decode attention is HBM-bandwidth-bound (the whole cache is read
    once per token); this kernel streams K/V blocks through VMEM with
    the query row resident and masks by the dynamic length, so the same
    compiled program serves every position. Inference-only (no vjp).
    """
    o, _ = flash_decode_with_lse(q, k_cache, v_cache, lengths,
                                 block_k=block_k, interpret=interpret)
    return o


def dense_decode_with_lse(q, k_cache, v_cache, lengths):
    """(o [B, H, D] fp32, lse [B, H] fp32) by plain XLA ops — the same
    contract as flash_decode_with_lse, without Pallas.

    On a single v5e chip this BEATS the Pallas decode kernel at serving
    shapes (chip: 4075 tok/s dense vs 841 flash at bs8/d512/8L/4096 —
    BENCH_TABLE decode_dense/decode_flash): decode attention reads
    [1, T] scores, so there is no T x T materialization for a flash
    schedule to avoid, and XLA runs the whole cache read as one fused
    batched contraction while the kernel pays per-grid-step overhead
    on thousands of tiny (rows<=G, D) blocks. GQA reads the cache once
    per GROUP via the grouped einsum — no materialized repeat. Rows
    with zero valid keys return o=0, lse~-1e30 and drop out of the
    cross-shard combine.

    models.transformer._decode_attention carries the same grouped
    contraction with a deliberately different numeric profile (PV at
    cache dtype, no lse — the single-chip serving hot loop); a
    masking/scaling fix in either likely applies to both."""
    b, h, d = q.shape
    t, kvh = k_cache.shape[1], k_cache.shape[2]
    if h % kvh:
        raise ValueError("query heads %d must be a multiple of KV "
                         "heads %d" % (h, kvh))
    g = h // kvh
    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (b,))
    qg = q.reshape(b, kvh, g, d).astype(jnp.float32)
    s = jnp.einsum("bkgd,btkd->bkgt", qg,
                   k_cache.astype(jnp.float32)) / (d ** 0.5)
    valid = jnp.arange(t)[None, :] < lengths[:, None]
    vmask = valid[:, None, None, :]
    s = jnp.where(vmask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.where(vmask, jnp.exp(s - m[..., None]), 0.0)
    l = p.sum(-1)
    o = jnp.einsum("bkgt,btkd->bkgd", p, v_cache.astype(jnp.float32))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return o.reshape(b, h, d), lse.reshape(b, h)


def flash_decode_with_lse(q, k_cache, v_cache, lengths, block_k=None,
                          interpret=None):
    """flash_decode returning (o [B, H, D], lse [B, H]) — the partial
    result + its log-sum-exp, combinable across cache shards:

        m = max_i(lse_i); w_i = exp(lse_i - m)
        o = sum_i(w_i * o_i) / sum_i(w_i)

    This is the flash-decoding decomposition for sequence-parallel
    caches (each device holds a slice of the sequence).

    GQA: when the caches carry KVH < H heads (H divisible by KVH),
    query heads [j*G:(j+1)*G] share cache head j (G = H // KVH) and
    each cache block is read once per GROUP, not per query head — the
    KV-cache bandwidth saving grouped-query attention exists for.

    block_k=None picks the largest of (512, 256, 128) dividing the
    cache length (falling back to the full length): the grid runs
    (B*KVH) x (Tmax/block_k) sequential steps, so small blocks pay
    per-step overhead on tiny (G, D) tiles — the chip A/B that
    retired this kernel as the sp default measured it at 128.
    dense_decode_with_lse is the plain-XLA form that usually wins."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, heads, head_dim = q.shape
    t_max, kv_heads = k_cache.shape[1], k_cache.shape[2]
    if heads % kv_heads:
        raise ValueError("query heads %d must be a multiple of KV "
                         "heads %d" % (heads, kv_heads))
    g = heads // kv_heads
    if block_k is None:
        # memoized per shape (the choice is pure shape math, but it
        # used to sit on the per-call path): largest of (512, 256, 128)
        # dividing the cache length, else one full-length block — the
        # same cache the paged decode kernel keys its block_k on
        block_k = choose_block_k(
            t_max, shape_key=("flash_decode", b, kv_heads, g, head_dim))
    block_k = min(block_k, t_max)
    if t_max % block_k:
        raise ValueError("block_k %d must divide the cache length %d"
                         % (block_k, t_max))
    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (b,))
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(
        b * kv_heads, x.shape[1], head_dim)
    o, lse = _flash_decode_bh(
        q.reshape(b, kv_heads, g, head_dim).reshape(
            b * kv_heads, g, head_dim),
        to_bh(k_cache), to_bh(v_cache),
        jnp.repeat(lengths, kv_heads), block_k, interpret)
    return (o.reshape(b, heads, head_dim),
            lse[..., 0].reshape(b, heads))


def _adjust_block(block, seq, name):
    """kernels.common.adjust_block with this family's name in the
    warning (kept as a module symbol — tests and callers import it)."""
    return _adjust_block_common(block, seq, name,
                                family="flash_attention")


def flash_attention(q, k, v, causal=False, block_q=None, block_k=None,
                    interpret=None):
    """Multi-head attention over [B, T, H, D] tensors.

    Equivalent to softmax(q k^T / sqrt(D)) v computed blockwise in
    VMEM with K/V streamed from HBM (sequence length is HBM-bounded).
    Differentiable via the flash backward (recompute + saved logsumexp).
    Block sizes clamp to the sequence lengths; sequences must be
    divisible by the (clamped) blocks. `interpret` defaults to True off
    TPU so the same code runs everywhere.

    block_q/block_k default to 128 (overridable per-process via
    MXNET_FLASH_BLOCK_Q / MXNET_FLASH_BLOCK_K): the grid runs
    (B*H) x (Tq/block_q) x (Tk/block_k) sequential steps, so small
    batch*heads with long T pays per-step overhead that bigger tiles
    amortize — a measurable A/B knob, same class as the decode
    kernel's block_k finding."""
    if block_q is None:
        block_q = int(os.environ.get("MXNET_FLASH_BLOCK_Q", "128"))
    if block_k is None:
        block_k = int(os.environ.get("MXNET_FLASH_BLOCK_K", "128"))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    # clamp to the sequence, then gcd-adjust a non-dividing block —
    # one deterministic rule for explicit args, env overrides, and
    # short/odd smoke shapes alike (callers need no block math of
    # their own). A collapsing gcd (e.g. prime T) would silently build
    # a pathologically fine (B*H) x T x T grid, so blocks that fall
    # below _MIN_BLOCK fall back to ONE full-sequence block with a
    # warning instead (ADVICE r5).
    block_q = _adjust_block(block_q, seq_q, "block_q")
    block_k = _adjust_block(block_k, seq_k, "block_k")
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(
        b * heads, x.shape[1], head_dim)
    out = _flash_bh(to_bh(q), to_bh(k), to_bh(v), causal,
                    block_q, block_k, interpret)
    return out.reshape(b, heads, seq_q, head_dim).transpose(0, 2, 1, 3)
