"""Blocked (flash) attention as a Pallas TPU kernel.

Softmax(QK^T)V without materialising the [Tq, Tk] score matrix in HBM:
each grid step owns one query block in VMEM and streams key/value
blocks, maintaining the online-softmax running max/denominator. This is
the kernel counterpart of parallel/ring.py's jnp-level blockwise
attention — the ring layer rotates K/V shards across devices, and this
kernel is the dense per-device block compute.

Layout: the (batch, head) pair is the leading grid axis, query blocks
the second; K/V for the pair sit in VMEM whole (fine up to a few
thousand keys at typical head dims; the ring layer keeps per-device
sequence shards in that regime).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale,
                 seq_k):
    # q_ref: [block_q, D]; k_ref/v_ref: [Tk, D]; o_ref: [block_q, D]
    block_q, head_dim = q_ref.shape
    q = q_ref[...].astype(jnp.float32) * scale
    q_start = pl.program_id(1) * block_q

    def body(kb, carry):
        o, m, l = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = alpha * l + p.sum(axis=1)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        o_new = alpha[:, None] * o + pv
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    num_kb = seq_k // block_k
    if causal:
        # blocks strictly above the diagonal contribute nothing; bound
        # the stream at the query block's last row
        last = (q_start + block_q + block_k - 1) // block_k
        num_kb = jnp.minimum(num_kb, last)
    o, m, l = jax.lax.fori_loop(0, num_kb, body, (o0, m0, l0))
    o_ref[...] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q",
                                             "block_k", "interpret"))
def _flash_bh(q, k, v, causal, block_q, block_k, interpret):
    """q/k/v: [BH, T, D] with T divisible by the block sizes."""
    bh, seq_q, head_dim = q.shape
    seq_k = k.shape[1]
    scale = 1.0 / (head_dim ** 0.5)
    kernel = functools.partial(_attn_kernel, block_k=block_k,
                               causal=causal, scale=scale, seq_k=seq_k)
    return pl.pallas_call(
        kernel,
        grid=(bh, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, head_dim),
                         lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((None, seq_k, head_dim), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((None, seq_k, head_dim), lambda b, qi: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, head_dim),
                               lambda b, qi: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


def flash_attention(q, k, v, causal=False, block_q=128, block_k=128,
                    interpret=None):
    """Multi-head attention over [B, T, H, D] tensors.

    Equivalent to softmax(q k^T / sqrt(D)) v computed blockwise in
    VMEM. Block sizes clamp to the sequence lengths; sequences must be
    divisible by the (clamped) blocks. `interpret` defaults to True off
    TPU so the same code runs everywhere.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            "sequence lengths (%d, %d) must divide by blocks (%d, %d)"
            % (seq_q, seq_k, block_q, block_k))
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(
        b * heads, x.shape[1], head_dim)
    out = _flash_bh(to_bh(q), to_bh(k), to_bh(v), causal,
                    block_q, block_k, interpret)
    return out.reshape(b, heads, seq_q, head_dim).transpose(0, 2, 1, 3)
