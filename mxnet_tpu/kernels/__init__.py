"""Hand-written Pallas TPU kernels.

The reference ships hand kernels where its compilers fell short (CUDA
.cu files, cuDNN call-outs); here XLA covers almost everything and this
package holds the few deliberate exceptions, written with Pallas
(MXU/VMEM-aware blocking). Kernels run compiled on TPU and in Pallas
interpret mode elsewhere, so their tests execute on any backend.
"""

from .flash_attention import (flash_attention, flash_decode,
                              dense_decode_with_lse)
from .paged_decode import paged_attention

__all__ = ["flash_attention", "flash_decode",
           "dense_decode_with_lse", "paged_attention"]
