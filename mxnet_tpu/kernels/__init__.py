"""Hand-written Pallas TPU kernels.

The reference ships hand kernels where its compilers fell short (CUDA
.cu files, cuDNN call-outs); here XLA covers almost everything and this
package holds the few deliberate exceptions, written with Pallas
(MXU/VMEM-aware blocking). Kernels run compiled on TPU and in Pallas
interpret mode elsewhere, so their tests execute on any backend.
"""

from .flash_attention import (flash_attention, flash_decode,
                              dense_decode_with_lse)

__all__ = ["flash_attention", "flash_decode",
           "dense_decode_with_lse"]
