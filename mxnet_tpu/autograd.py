"""Autograd — imperative differentiation.

Reference: src/imperative/imperative.cc (tape via AGInfo nodes,
Imperative::RecordOp / Backward) and python/mxnet/autograd.py (record /
pause / train_mode scopes, backward, grad, custom Function).

TPU-native design: instead of re-deriving a gradient graph from per-op
FGradient registrations, each recorded op calls jax.vjp at invoke time —
the pullback closure (with its residuals living on device) IS the tape
node. backward() walks nodes in reverse execution order accumulating
cotangents; exactness comes from XLA's AD rules rather than 345 hand-written
gradient registrations.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from . import engine
from .observability import core as _obs

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
    return _state


class TapeNode:
    """One recorded op: pullback + input/output bookkeeping
    (the analogue of nnvm::Node + AGInfo, include/mxnet/imperative.h:42-79)."""

    __slots__ = ("vjp_fn", "inputs", "num_outputs", "cotangents", "out_shapes",
                 "out_dtypes", "op_name")

    def __init__(self, vjp_fn, inputs, num_outputs, out_shapes, out_dtypes,
                 op_name=""):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # list of NDArray (kept alive for leaves)
        self.num_outputs = num_outputs
        self.cotangents = [None] * num_outputs
        self.out_shapes = out_shapes
        self.out_dtypes = out_dtypes
        self.op_name = op_name


# ------------------------------------------------------------- scopes --
class _RecordingStateScope:
    def __init__(self, is_record, train_mode):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode
        self._prev = None

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._enter_is_record is not None:
            st.recording = self._enter_is_record
        if self._enter_train_mode is not None:
            st.training = self._enter_train_mode
        return self

    def __exit__(self, *a):
        st = _st()
        st.recording, st.training = self._prev


def record(train_mode=True):
    """python/mxnet/autograd.py:93 — enter recording (and by default train)
    scope."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    st = _st()
    prev, st.recording = st.recording, is_record
    return prev


def set_training(train):
    st = _st()
    prev, st.training = st.training, train
    return prev


# --------------------------------------------------------------- tape --
def _tape():
    return _st().tape


def _record_node(node):
    _st().tape.append(node)


def mark_variables(variables, gradients, grad_reqs="write"):
    """python/mxnet/autograd.py mark_variables."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, r in zip(variables, gradients, grad_reqs):
        v._mark_variable(g, r)


def _collect(outputs):
    out = []
    for o in outputs:
        if o._ag_node is None and not o._ag_leaf:
            raise MXNetError(
                "cannot differentiate %s: it was not computed inside an "
                "autograd.record() scope" % repr(o))
        out.append(o)
    return out


def backward(outputs, head_grads=None, retain_graph=False, train_mode=True):
    """Reverse pass (analogue of Imperative::Backward,
    src/imperative/imperative.cc:280): reverse-iterate the tape, feed each
    node its accumulated output cotangents, pull back to inputs."""
    with _obs.span("backward", cat="step", heads=len(outputs)
                   if isinstance(outputs, (list, tuple)) else 1):
        return _backward_impl(outputs, head_grads, retain_graph,
                              train_mode)


def _backward_impl(outputs, head_grads=None, retain_graph=False,
                   train_mode=True):
    from .ndarray import NDArray

    if isinstance(outputs, NDArray):
        outputs = [outputs]
    if head_grads is not None and isinstance(head_grads, NDArray):
        head_grads = [head_grads]
    outputs = _collect(outputs)

    tape = _tape()
    # seed cotangents
    grad_acc = {}  # id(leaf NDArray) -> (leaf, jnp grad)

    def add_ct(node, idx, ct):
        cur = node.cotangents[idx]
        node.cotangents[idx] = ct if cur is None else cur + ct

    needed = set()
    for i, o in enumerate(outputs):
        hg = None
        if head_grads is not None and head_grads[i] is not None:
            hg = head_grads[i]._data
        else:
            hg = jnp.ones(o.shape, dtype=o.dtype)
        if o._ag_leaf and o._ag_node is None:
            _acc_leaf(o, hg, grad_acc)
            continue
        node, idx = o._ag_node
        add_ct(node, idx, hg)
        needed.add(id(node))

    # mark ancestry (reverse sweep marks needed nodes as it goes)
    for node in reversed(tape):
        if id(node) not in needed:
            # might become needed if a later-position node feeds it... cannot:
            # tape order == execution order so consumers come after producers;
            # reverse order visits consumers first and marks producers below.
            if all(c is None for c in node.cotangents):
                continue
        cts = []
        for k in range(node.num_outputs):
            c = node.cotangents[k]
            if c is None:
                c = jnp.zeros(node.out_shapes[k], dtype=node.out_dtypes[k])
            cts.append(c)
        ct_arg = tuple(cts) if node.num_outputs > 1 else cts[0]
        in_grads = node.vjp_fn(ct_arg)
        engine.sync_if_needed([g for g in in_grads
                               if hasattr(g, "block_until_ready")])
        for inp, g in zip(node.inputs, in_grads):
            if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                continue
            if inp._ag_leaf:
                _acc_leaf(inp, g, grad_acc)
            if inp._ag_node is not None:
                pnode, pidx = inp._ag_node
                add_ct(pnode, pidx, g)
                needed.add(id(pnode))
        if not retain_graph:
            node.cotangents = [None] * node.num_outputs

    # write accumulated grads into .grad respecting grad_req
    for leaf, g in grad_acc.values():
        if leaf._grad_req == "add":
            leaf._grad._data = leaf._grad._data + g.astype(leaf._grad.dtype)
        elif leaf._grad_req == "write":
            leaf._grad._data = g.astype(leaf._grad.dtype)
        leaf._fresh_grad = True  # consumed by Trainer stale-grad detection

    if not retain_graph:
        tape.clear()


def _acc_leaf(leaf, g, grad_acc):
    if leaf._grad is None or leaf._grad_req == "null":
        return
    cur = grad_acc.get(id(leaf))
    grad_acc[id(leaf)] = (leaf, g if cur is None else cur[1] + g)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """python/mxnet/autograd.py grad — return grads instead of writing
    .grad. create_graph (higher-order) is supported by replay through
    jax.grad at the CachedOp level; here first-order only."""
    from .ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
    saved = [(v._grad, v._grad_req) for v in variables]
    for v in variables:
        if v._grad is None:
            v._mark_variable(None, "write")
        v._grad_req = "write"
        from .ndarray import zeros
        v._grad = zeros(v.shape, dtype=v.dtype)
    backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
    out = [v._grad for v in variables]
    for v, (g, r) in zip(variables, saved):
        v._grad, v._grad_req = (g, r) if g is not None else (v._grad, r)
    return out


def get_symbol(x):
    raise MXNetError("autograd.get_symbol: the TPU build records jax vjp "
                     "closures, not nnvm symbols; use gluon.HybridBlock "
                     "tracing to obtain a Symbol")


class Function:
    """Custom differentiable function (python/mxnet/autograd.py:Function).

    Subclass and implement forward(self, *inputs) and
    backward(self, *output_grads), both over NDArray.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def __call__(self, *inputs):
        from .ndarray import NDArray, array
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)

        if is_recording() and any(i._requires_tape() for i in inputs):
            func = self

            def vjp_fn(cts):
                cts_list = [cts] if len(outs) == 1 else list(cts)
                with pause():
                    igrads = func.backward(
                        *[NDArray(c) for c in cts_list])
                if not isinstance(igrads, (list, tuple)):
                    igrads = [igrads]
                return [g._data if g is not None else None for g in igrads]

            node = TapeNode(vjp_fn, list(inputs), len(outs),
                            [o.shape for o in outs], [o.dtype for o in outs],
                            op_name=type(self).__name__)
            _record_node(node)
            for k, o in enumerate(outs):
                o._ag_node = (node, k)
        return outs[0] if single else outs

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError
