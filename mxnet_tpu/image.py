"""mx.image — image IO, processing and augmentation pipeline.

Reference: python/mxnet/image/image.py (2504 LoC Python-side pipeline)
and the C++ augmenters (src/io/image_aug_default.cc:565). TPU-native
design: decode/augment stay on the host CPU in numpy/cv2 (the chip has
no JPEG engine), producing batched NDArrays that transfer to device
once per batch; device-side normalize/flip also exist as jax ops for
in-graph use (ops applied under jit fuse into the input pipeline).
"""

import os
import random as pyrandom

import numpy as np

try:
    import cv2
except ImportError:  # pragma: no cover
    cv2 = None

from . import ndarray as nd
from .base import MXNetError
from .io import DataIter, DataBatch, DataDesc

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "scale_down", "copyMakeBorder",
           "Augmenter", "SequentialAug", "RandomOrderAug", "ResizeAug",
           "ForceResizeAug", "CastAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "RandomGrayAug", "HorizontalFlipAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "CreateAugmenter",
           "ImageIter"]


def _require_cv2():
    if cv2 is None:
        raise MXNetError("cv2 (OpenCV) is required for image decode ops")


def _as_np(img):
    return img.asnumpy() if isinstance(img, nd.NDArray) else np.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to an HWC uint8 NDArray
    (reference imdecode: python/mxnet/image/image.py:imdecode)."""
    _require_cv2()
    if isinstance(buf, (bytes, bytearray)):
        buf = np.frombuffer(buf, dtype=np.uint8)
    img = cv2.imdecode(buf, int(flag))
    if img is None:
        raise MXNetError("Decoding failed. Invalid image buffer.")
    if to_rgb and img.ndim == 3 and img.shape[2] == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(img, dtype="uint8")


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    _require_cv2()
    img = cv2.resize(_as_np(src), (w, h), interpolation=int(interp))
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(img, dtype=img.dtype.name)


def scale_down(src_size, size):
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    h, w = _as_np(src).shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def copyMakeBorder(src, top, bot, left, right, border_type=0, values=0):
    _require_cv2()
    img = cv2.copyMakeBorder(_as_np(src), top, bot, left, right,
                             border_type, value=values)
    return nd.array(img, dtype=img.dtype.name)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _as_np(src)[y0:y0 + h, x0:x0 + w]
    out = nd.array(arr, dtype=arr.dtype.name)
    if size is not None and (w, h) != size:
        out = imresize(out, *size, interp=interp)
    return out


def random_crop(src, size, interp=2):
    h, w = _as_np(src).shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = _as_np(src).shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = _as_np(src).shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    src = src.astype("float32") if isinstance(src, nd.NDArray) \
        else nd.array(src, dtype="float32")
    out = src - nd.array(np.asarray(mean, np.float32))
    if std is not None:
        out = out / nd.array(np.asarray(std, np.float32))
    return out


# ----------------------------------------------------------- augmenters --
class Augmenter(object):
    """Image augmenter base (image.py Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super(SequentialAug, self).__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super(RandomOrderAug, self).__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super(ResizeAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super(ForceResizeAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, *self.size, interp=self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super(RandomCropAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super(RandomSizedCropAug, self).__init__(size=size, area=area,
                                                 ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super(CenterCropAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super(HorizontalFlipAug, self).__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = _as_np(src)[:, ::-1]
            return nd.array(arr.copy(), dtype=arr.dtype.name)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super(CastAug, self).__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ) if isinstance(src, nd.NDArray) \
            else nd.array(_as_np(src), dtype=self.typ)


# ITU-R BT.601 luma weights, shared by the photometric jitter family
_LUMA = np.array([[[0.299, 0.587, 0.114]]], np.float32)


class _PhotometricJitterAug(Augmenter):
    """Shared machinery: blend the image toward a reference signal by a
    random strength drawn from U(1-jitter, 1+jitter)."""

    def __init__(self, jitter, **kwargs):
        super(_PhotometricJitterAug, self).__init__(**kwargs)
        self.jitter = jitter

    def reference(self, arr):
        """The signal to blend toward at alpha -> 0; subclasses override."""
        raise NotImplementedError

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.jitter, self.jitter)
        arr = _as_np(src).astype(np.float32)
        return nd.array(arr * alpha + self.reference(arr) * (1.0 - alpha))


class BrightnessJitterAug(_PhotometricJitterAug):
    """Blend toward black."""

    def __init__(self, brightness):
        super(BrightnessJitterAug, self).__init__(brightness,
                                                  brightness=brightness)
        self.brightness = brightness

    def reference(self, arr):
        return 0.0


class ContrastJitterAug(_PhotometricJitterAug):
    """Blend toward the image's mean luma (a flat gray)."""

    def __init__(self, contrast):
        super(ContrastJitterAug, self).__init__(contrast, contrast=contrast)
        self.contrast = contrast

    def reference(self, arr):
        return (arr * _LUMA).sum() * (3.0 / arr.size)


class SaturationJitterAug(_PhotometricJitterAug):
    """Blend toward the per-pixel luma (desaturate)."""

    def __init__(self, saturation):
        super(SaturationJitterAug, self).__init__(saturation,
                                                  saturation=saturation)
        self.saturation = saturation

    def reference(self, arr):
        return (arr * _LUMA).sum(axis=2, keepdims=True)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super(HueJitterAug, self).__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]])
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]])

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]])
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        arr = _as_np(src).astype(np.float32)
        return nd.array(np.dot(arr, t))


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super(ColorJitterAug, self).__init__(ts)


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super(LightingAug, self).__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval)
        self.eigvec = np.asarray(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return src + nd.array(rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super(ColorNormalizeAug, self).__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _mat = np.array([[0.21, 0.21, 0.21],
                     [0.72, 0.72, 0.72],
                     [0.07, 0.07, 0.07]], np.float32)

    def __init__(self, p):
        super(RandomGrayAug, self).__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.array(np.dot(_as_np(src).astype(np.float32),
                                   self._mat))
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list factory (image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = np.asarray(mean)
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = np.asarray(std)
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Image iterator over .rec files or path-imglist with augmenters
    (reference python/mxnet/image/image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", last_batch_handle="pad",
                 **kwargs):
        super(ImageIter, self).__init__()
        from . import recordio
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            idx_path = path_imgidx or \
                os.path.splitext(path_imgrec)[0] + ".idx"
            self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec,
                                                     "r")
            self.seq = list(self.imgrec.keys)
        else:
            if path_imglist:
                imglist = {}
                with open(path_imglist) as fin:
                    for line in fin:
                        line = line.strip().split("\t")
                        label = np.array(line[1:-1], dtype=np.float32)
                        imglist[int(line[0])] = (label, line[-1])
            else:
                imglist = {i: (np.array(item[0], dtype=np.float32)
                               if not np.isscalar(item[0])
                               else np.array([item[0]], dtype=np.float32),
                               item[1])
                           for i, item in enumerate(imglist)}
            self.imglist = imglist
            self.seq = list(imglist.keys())
        self.path_root = path_root
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.label_width = label_width
        self.shuffle = shuffle
        if num_parts > 1:
            self.seq = self.seq[part_index::num_parts]
        self.auglist = aug_list if aug_list is not None \
            else CreateAugmenter(data_shape, **kwargs)
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + data_shape, "float32")]
        if label_width > 1:
            self.provide_label = [DataDesc(label_name,
                                           (batch_size, label_width),
                                           "float32")]
        else:
            self.provide_label = [DataDesc(label_name, (batch_size,),
                                           "float32")]
        self.last_batch_handle = last_batch_handle
        self._cache = []
        self.cur = 0
        self.reset()

    def reset(self):
        if self.shuffle:
            pyrandom.shuffle(self.seq)
        self.cur = 0

    def next_sample(self):
        from . import recordio
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            s = self.imgrec.read_idx(idx)
            header, img = recordio.unpack(s)
            return header.label, img
        label, fname = self.imglist[idx]
        with open(os.path.join(self.path_root or "", fname), "rb") as f:
            return label, f.read()

    def _decoded_sample(self):
        """Next (CHW float array, label row), from the rollover cache
        first."""
        if self._cache:
            return self._cache.pop(0)
        label, s = self.next_sample()
        img = imdecode(s)
        for aug in self.auglist:
            img = aug(img)
        return _as_np(img).transpose(2, 0, 1), label

    def next(self):
        batch_data = np.zeros((self.batch_size,) + self.data_shape,
                              np.float32)
        batch_label = np.zeros((self.batch_size, self.label_width),
                               np.float32)
        rows = []
        try:
            while len(rows) < self.batch_size:
                rows.append(self._decoded_sample())
        except StopIteration:
            if not rows:
                raise
            if self.last_batch_handle == "discard":
                raise
            if self.last_batch_handle == "roll_over":
                self._cache = rows  # ragged remainder joins next epoch
                raise StopIteration
            # 'pad': fill with real samples wrapped from the epoch start
            # (reference ImageIter semantics) — pad stays set so aware
            # consumers can discard them
            pad = self.batch_size - len(rows)
            self.cur = 0
            while len(rows) < self.batch_size:
                if self.cur >= len(self.seq):
                    self.cur = 0  # dataset smaller than the pad: keep cycling
                rows.append(self._decoded_sample())
            self.cur = len(self.seq)  # next() must still end the epoch
            for i, (arr, label) in enumerate(rows):
                batch_data[i] = arr
                batch_label[i] = label
            label_out = batch_label[:, 0] if self.label_width == 1 \
                else batch_label
            return DataBatch(data=[nd.array(batch_data)],
                             label=[nd.array(label_out)], pad=pad)
        for i, (arr, label) in enumerate(rows):
            batch_data[i] = arr
            batch_label[i] = label
        label_out = batch_label[:, 0] if self.label_width == 1 \
            else batch_label
        return DataBatch(data=[nd.array(batch_data)],
                         label=[nd.array(label_out)],
                         pad=self.batch_size - len(rows))
