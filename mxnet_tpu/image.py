"""mx.image — image IO, processing and augmentation pipeline.

Reference: python/mxnet/image/image.py (2504 LoC Python-side pipeline)
and the C++ augmenters (src/io/image_aug_default.cc:565). TPU-native
design: decode/augment stay on the host CPU in numpy/cv2 (the chip has
no JPEG engine), producing batched NDArrays that transfer to device
once per batch; device-side normalize/flip also exist as jax ops for
in-graph use (ops applied under jit fuse into the input pipeline).
"""

import math
import os
import random as pyrandom

import numpy as np

try:
    import cv2
except ImportError:  # pragma: no cover
    cv2 = None

from . import ndarray as nd
from .base import MXNetError
from .io import DataIter, DataBatch, DataDesc

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "scale_down", "copyMakeBorder",
           "Augmenter", "SequentialAug", "RandomOrderAug", "ResizeAug",
           "ForceResizeAug", "CastAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "RandomGrayAug", "HorizontalFlipAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "CreateAugmenter",
           "ImageIter"]


def _require_cv2():
    if cv2 is None:
        raise MXNetError("cv2 (OpenCV) is required for image decode ops")


def _as_np(img):
    return img.asnumpy() if isinstance(img, nd.NDArray) else np.asarray(img)


def _like(src, arr):
    """Return `arr` in the container type of `src`: the public API is
    NDArray-in/NDArray-out (reference image.py), but the iterator hot
    loop feeds plain numpy through the augmenter chain — per-image
    nd.array wrapping costs a device_put each and dominated the pipeline
    (benchmark/input_pipeline_bench.py: ~390 img/s before, decode alone
    is ~2,700 img/s on one core)."""
    if isinstance(src, nd.NDArray):
        return nd.array(arr, dtype=arr.dtype.name)
    return arr


def _imdecode_np(buf, flag=1, to_rgb=True):
    """cv2-only decode to an HWC uint8 numpy array — safe on worker
    threads (no jax dispatch)."""
    _require_cv2()
    if isinstance(buf, (bytes, bytearray)):
        buf = np.frombuffer(buf, dtype=np.uint8)
    img = cv2.imdecode(buf, int(flag))
    if img is None:
        raise MXNetError("Decoding failed. Invalid image buffer.")
    if to_rgb and img.ndim == 3 and img.shape[2] == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to an HWC uint8 NDArray
    (reference imdecode: python/mxnet/image/image.py:imdecode)."""
    return nd.array(_imdecode_np(buf, flag, to_rgb), dtype="uint8")


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    _require_cv2()
    img = cv2.resize(_as_np(src), (w, h), interpolation=int(interp))
    if img.ndim == 2:
        img = img[:, :, None]
    return _like(src, img)


def scale_down(src_size, size):
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    h, w = _as_np(src).shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def copyMakeBorder(src, top, bot, left, right, border_type=0, values=0):
    _require_cv2()
    img = cv2.copyMakeBorder(_as_np(src), top, bot, left, right,
                             border_type, value=values)
    return _like(src, img)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _as_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        arr = _as_np(imresize(arr, *size, interp=interp))
    return _like(src, arr)


def random_crop(src, size, interp=2):
    h, w = _as_np(src).shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = _as_np(src).shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    h, w = _as_np(src).shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = _as_np(src).astype(np.float32)
    out = arr - np.asarray(_as_np(mean), np.float32)
    if std is not None:
        out = out / np.asarray(_as_np(std), np.float32)
    return _like(src, out)




def _nchw_f32(batch_np):
    """(B, H, W, C) host stack -> (B, C, H, W) float32 jax array via one
    jitted XLA op. On an accelerator the uint8 stack transfers as-is
    (4x fewer bytes than float) and the cast+layout change runs on
    device; on CPU it is a single vectorized XLA kernel."""
    import jax
    import jax.numpy as jnp
    from ._discover import ensure_backend
    ensure_backend()  # may be the process's first jax touch (wedge guard)
    global _nchw_jit
    if _nchw_jit is None:
        _nchw_jit = jax.jit(
            lambda x: jnp.transpose(x.astype(jnp.float32), (0, 3, 1, 2)))
    return _nchw_jit(np.ascontiguousarray(batch_np))


_nchw_jit = None


def _np_safe_aug(aug):
    """True when an augmenter (and everything it wraps) is defined in
    this module — such chains are type-preserving, so the iterator can
    feed plain numpy through them (no per-image device_put). User
    subclasses fall back to the NDArray contract."""
    if type(aug).__module__ != __name__:
        return False
    children = []
    for attr in ("ts", "aug_list"):
        children.extend(getattr(aug, attr, ()) or ())
    if getattr(aug, "augmenter", None) is not None:
        children.append(aug.augmenter)
    return all(_np_safe_aug(c) for c in children)


# ----------------------------------------------------------- augmenters --
class Augmenter(object):
    """Image augmenter base (image.py Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super(SequentialAug, self).__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super(RandomOrderAug, self).__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super(ResizeAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super(ForceResizeAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, *self.size, interp=self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super(RandomCropAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super(RandomSizedCropAug, self).__init__(size=size, area=area,
                                                 ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super(CenterCropAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super(HorizontalFlipAug, self).__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            # copy: downstream cv2 augs reject negative-stride views
            return _like(src, np.ascontiguousarray(_as_np(src)[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super(CastAug, self).__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        if isinstance(src, nd.NDArray):
            return src.astype(self.typ)
        return np.asarray(src).astype(self.typ)


# ITU-R BT.601 luma weights, shared by the photometric jitter family
_LUMA = np.array([[[0.299, 0.587, 0.114]]], np.float32)


class _PhotometricJitterAug(Augmenter):
    """Shared machinery: blend the image toward a reference signal by a
    random strength drawn from U(1-jitter, 1+jitter)."""

    def __init__(self, jitter, **kwargs):
        super(_PhotometricJitterAug, self).__init__(**kwargs)
        self.jitter = jitter

    def reference(self, arr):
        """The signal to blend toward at alpha -> 0; subclasses override."""
        raise NotImplementedError

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.jitter, self.jitter)
        arr = _as_np(src).astype(np.float32)
        return _like(src, arr * alpha + self.reference(arr) * (1.0 - alpha))


class BrightnessJitterAug(_PhotometricJitterAug):
    """Blend toward black."""

    def __init__(self, brightness):
        super(BrightnessJitterAug, self).__init__(brightness,
                                                  brightness=brightness)
        self.brightness = brightness

    def reference(self, arr):
        return 0.0


class ContrastJitterAug(_PhotometricJitterAug):
    """Blend toward the image's mean luma (a flat gray)."""

    def __init__(self, contrast):
        super(ContrastJitterAug, self).__init__(contrast, contrast=contrast)
        self.contrast = contrast

    def reference(self, arr):
        return (arr * _LUMA).sum() * (3.0 / arr.size)


class SaturationJitterAug(_PhotometricJitterAug):
    """Blend toward the per-pixel luma (desaturate)."""

    def __init__(self, saturation):
        super(SaturationJitterAug, self).__init__(saturation,
                                                  saturation=saturation)
        self.saturation = saturation

    def reference(self, arr):
        return (arr * _LUMA).sum(axis=2, keepdims=True)


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super(HueJitterAug, self).__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]])
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]])

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]])
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        arr = _as_np(src).astype(np.float32)
        return _like(src, np.dot(arr, t).astype(np.float32))


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super(ColorJitterAug, self).__init__(ts)


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super(LightingAug, self).__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval)
        self.eigvec = np.asarray(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return _like(src, _as_np(src) + rgb.astype(np.float32))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super(ColorNormalizeAug, self).__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _mat = np.array([[0.21, 0.21, 0.21],
                     [0.72, 0.72, 0.72],
                     [0.07, 0.07, 0.07]], np.float32)

    def __init__(self, p):
        super(RandomGrayAug, self).__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return _like(src, np.dot(_as_np(src).astype(np.float32),
                                     self._mat))
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list factory (image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = np.asarray(mean)
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = np.asarray(std)
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Image iterator over .rec files or path-imglist with augmenters
    (reference python/mxnet/image/image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", last_batch_handle="pad",
                 **kwargs):
        super(ImageIter, self).__init__()
        from . import recordio
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            idx_path = path_imgidx or \
                os.path.splitext(path_imgrec)[0] + ".idx"
            self.imgrec = recordio.MXIndexedRecordIO(idx_path, path_imgrec,
                                                     "r")
            self.seq = list(self.imgrec.keys)
            if not self.seq:
                # a wrong/missing .idx silently yields an empty epoch —
                # fail loudly instead (tools/im2rec writes 'name.idx'
                # next to 'name.rec'; MXIndexedRecordIO(w) with an
                # explicit idx path may have put it elsewhere)
                raise MXNetError(
                    "record index %r has no entries — wrong or missing "
                    ".idx for %r? (pass path_imgidx explicitly)"
                    % (idx_path, path_imgrec))
        else:
            if path_imglist:
                imglist = {}
                with open(path_imglist) as fin:
                    for line in fin:
                        line = line.strip().split("\t")
                        label = np.array(line[1:-1], dtype=np.float32)
                        imglist[int(line[0])] = (label, line[-1])
            else:
                imglist = {i: (np.array(item[0], dtype=np.float32)
                               if not np.isscalar(item[0])
                               else np.array([item[0]], dtype=np.float32),
                               item[1])
                           for i, item in enumerate(imglist)}
            self.imglist = imglist
            self.seq = list(imglist.keys())
        self.path_root = path_root
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.label_width = label_width
        self.shuffle = shuffle
        if num_parts > 1:
            self.seq = self.seq[part_index::num_parts]
        self.preprocess_threads = int(kwargs.pop("preprocess_threads", 0))
        self.auglist = aug_list if aug_list is not None \
            else CreateAugmenter(data_shape, **kwargs)
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + data_shape, "float32")]
        if label_width > 1:
            self.provide_label = [DataDesc(label_name,
                                           (batch_size, label_width),
                                           "float32")]
        else:
            self.provide_label = [DataDesc(label_name, (batch_size,),
                                           "float32")]
        self.last_batch_handle = last_batch_handle
        self._cache = []
        self.cur = 0
        self.reset()

    def reset(self):
        if self.shuffle:
            pyrandom.shuffle(self.seq)
        self.cur = 0
        if getattr(self, "_pending", None):
            self._pending = []

    def next_sample(self):
        from . import recordio
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            s = self.imgrec.read_idx(idx)
            header, img = recordio.unpack(s)
            return header.label, img
        label, fname = self.imglist[idx]
        with open(os.path.join(self.path_root or "", fname), "rb") as f:
            return label, f.read()

    def _next_raw_decoded(self):
        """Next (label, decoded HWC uint8 array). With
        preprocess_threads > 0 the JPEG decode (the dominant cost; cv2
        releases the GIL) runs on a thread pool a batch ahead — the
        reference ImageRecordIter's threaded decode loop
        (iter_image_recordio_2.cc:76,146). Augmenters stay on the
        calling thread: several are jnp-backed and eager jax dispatch
        is not safe to fan out across threads. Shared by ImageIter and
        ImageDetIter (whose augmenters also transform labels)."""
        if self.preprocess_threads > 0:
            if getattr(self, "_pool", None) is None:
                import concurrent.futures as _cf
                # our pool replaces OpenCV's internal one: concurrent
                # cv2 calls from several threads deadlock its global
                # worker pool otherwise (same reason the reference pins
                # OMP threads around its decode loop)
                try:
                    cv2.setNumThreads(0)
                except Exception:
                    pass
                self._pool = _cf.ThreadPoolExecutor(self.preprocess_threads)
                self._pending = []
            depth = max(self.batch_size, 2 * self.preprocess_threads)
            try:
                while len(self._pending) < depth:
                    label, s = self.next_sample()
                    self._pending.append(
                        (label, self._pool.submit(_imdecode_np, s)))
            except StopIteration:
                pass
            if not self._pending:
                raise StopIteration
            label, fut = self._pending.pop(0)
            return label, fut.result()
        label, s = self.next_sample()
        return label, _imdecode_np(s)

    def _augs_np_fast(self):
        flag = getattr(self, "_np_fast", None)
        if flag is None:
            flag = all(_np_safe_aug(a) for a in self.auglist)
            self._np_fast = flag
        return flag

    def _decoded_sample(self):
        """Next (HWC array, label row), from the rollover cache first.
        Built-in augmenter chains run entirely in numpy; user augmenters
        get the reference's NDArray-in/NDArray-out contract (at
        per-image wrapping cost). Plain float32 CastAugs are deferred to
        the batched device-side conversion (every built-in augmenter
        upcasts internally as needed)."""
        if self._cache:
            return self._cache.pop(0)
        label, arr = self._next_raw_decoded()
        if self._augs_np_fast():
            img = arr
            for aug in self.auglist:
                if type(aug) is CastAug and aug.typ == "float32":
                    continue
                img = aug(img)
        else:
            img = nd.array(arr, dtype="uint8")
            for aug in self.auglist:
                img = aug(img)
        return _as_np(img), label

    def _label_batch_shape(self):
        """Trailing label dims of one batch row — (label_width,) here;
        ImageDetIter overrides with its (max_objects, object_width)."""
        return (self.label_width,)

    def _assemble(self, rows, pad):
        """Stack HWC rows and do ONE cast+NCHW transpose as a jitted XLA
        op: the host contributes a contiguous uint8 (or float) stack and
        the cast/layout change runs on the accelerator when one is
        attached (and as one vectorized XLA op on CPU). This replaces
        per-image float casts + strided CHW copies, which dominated the
        pipeline (benchmark/input_pipeline_bench.py)."""
        batch_np = np.stack([r[0] for r in rows])
        batch_label = np.zeros((self.batch_size,)
                               + self._label_batch_shape(), np.float32)
        for i, (_, label) in enumerate(rows):
            batch_label[i] = label
        label_out = batch_label[:, 0] if batch_label.ndim == 2 \
            and self.label_width == 1 else batch_label
        arr = _nchw_f32(batch_np)
        # label the context honestly: the jitted conversion leaves the
        # batch on the default device (accelerator when present)
        from .context import Context
        dev = arr.devices().pop() if hasattr(arr, "devices") else None
        if dev is None or dev.platform == "cpu":
            ctx = Context("cpu", 0)
        else:
            plat = {"cuda": "gpu", "rocm": "gpu"}.get(
                dev.platform, dev.platform)
            ctx = Context(plat if plat in ("gpu", "tpu") else "tpu", dev.id)
        data = nd.NDArray(arr, ctx)
        return DataBatch(data=[data], label=[nd.array(label_out)], pad=pad)

    def next(self):
        rows = []
        try:
            while len(rows) < self.batch_size:
                rows.append(self._decoded_sample())
        except StopIteration:
            if not rows:
                raise
            if self.last_batch_handle == "discard":
                raise
            if self.last_batch_handle == "roll_over":
                self._cache = rows  # ragged remainder joins next epoch
                raise StopIteration
            # 'pad': fill with real samples wrapped from the epoch start
            # (reference ImageIter semantics) — pad stays set so aware
            # consumers can discard them
            pad = self.batch_size - len(rows)
            self.cur = 0
            while len(rows) < self.batch_size:
                if self.cur >= len(self.seq):
                    self.cur = 0  # dataset smaller than the pad: keep cycling
                rows.append(self._decoded_sample())
            self.cur = len(self.seq)  # next() must still end the epoch
            if getattr(self, "_pending", None):
                # drop samples the pad-fill prefetched past the epoch
                # boundary: leftovers would keep next() serving forever
                self._pending = []
            return self._assemble(rows, pad)
        return self._assemble(rows, pad=self.batch_size - len(rows))


# ---------------------------------------------------------- detection --
# Reference: python/mxnet/image/detection.py — the SSD-style pipeline
# where every augmentation transforms the image AND its box labels.
# Label wire format (im2rec detection packing): [header_width A,
# object_width B, <extra header>, obj0[B], obj1[B], ...] with each
# object [cls_id, xmin, ymin, xmax, ymax] in normalized coordinates.

class DetAugmenter(object):
    """Base detection augmenter: __call__(src, label) -> (src, label)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection pipeline (labels
    pass through — only photometric/normalize augs are safe to borrow)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly apply exactly one of aug_list (or none, with skip_prob)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability p."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = _like(src, np.ascontiguousarray(_as_np(src)[:, ::-1]))
            out = label.copy()
            valid = out[:, 0] >= 0
            xmin = out[valid, 1].copy()
            out[valid, 1] = 1.0 - out[valid, 3]
            out[valid, 3] = 1.0 - xmin
            label = out
        return src, label


def _box_overlap_frac(boxes, crop):
    """Fraction of each box's area inside crop (x0, y0, x1, y1)."""
    ix = np.maximum(0.0, np.minimum(boxes[:, 3], crop[2])
                    - np.maximum(boxes[:, 1], crop[0]))
    iy = np.maximum(0.0, np.minimum(boxes[:, 4], crop[3])
                    - np.maximum(boxes[:, 2], crop[1]))
    inter = ix * iy
    area = np.maximum(1e-12, (boxes[:, 3] - boxes[:, 1])
                      * (boxes[:, 4] - boxes[:, 2]))
    return inter / area


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained to keep objects reasonably covered
    (reference python/mxnet/image/detection.py:237-269: sample up to
    max_attempts crops in the area/aspect ranges; a candidate is
    accepted only when the MINIMUM coverage over all overlapping valid
    objects exceeds min_object_covered; min_eject_coverage then applies
    to the ACCEPTED crop's label update, dropping objects whose
    remaining coverage is at or below it)."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = _as_np(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, math.sqrt(area * ratio))
            ch = min(1.0, math.sqrt(area / ratio))
            x0 = pyrandom.uniform(0, 1 - cw)
            y0 = pyrandom.uniform(0, 1 - ch)
            crop = (x0, y0, x0 + cw, y0 + ch)
            valid = label[:, 0] >= 0
            if not valid.any():
                break
            cov = _box_overlap_frac(label[valid], crop)
            # acceptance: min coverage over ALL overlapping objects must
            # exceed min_object_covered (reference
            # _check_satisfy_constraints: np.amin(coverages) >
            # min_object_covered over coverages > 0) — crops that
            # partially lose any object beyond the threshold are retried
            overlapping = cov[cov > 0]
            if overlapping.size == 0 or \
                    np.amin(overlapping) <= self.min_object_covered:
                continue
            # label update of the accepted crop: eject objects whose
            # coverage is at or below min_eject_coverage (reference
            # _update_labels: valid &= coverage > min_eject_coverage)
            keep = cov > self.min_eject_coverage
            if not keep.any():
                continue
            out = np.full_like(label, -1.0)
            kept = label[valid][keep].copy()
            # clip to the crop window and renormalize
            kept[:, 1] = (np.clip(kept[:, 1], x0, crop[2]) - x0) / cw
            kept[:, 3] = (np.clip(kept[:, 3], x0, crop[2]) - x0) / cw
            kept[:, 2] = (np.clip(kept[:, 2], y0, crop[3]) - y0) / ch
            kept[:, 4] = (np.clip(kept[:, 4], y0, crop[3]) - y0) / ch
            out[:len(kept)] = kept
            px0, py0 = int(x0 * w), int(y0 * h)
            px1, py1 = int(math.ceil(crop[2] * w)), \
                int(math.ceil(crop[3] * h))
            return _like(src, arr[py0:py1, px0:px1].copy()), out
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Place the image on a larger canvas (zoom-out) and rescale boxes."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = _as_np(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            ch = math.sqrt(area / ratio)
            cw = math.sqrt(area * ratio)
            if ch < 1.0 or cw < 1.0:
                continue
            nh, nw = int(h * ch), int(w * cw)
            y0 = pyrandom.randint(0, nh - h)
            x0 = pyrandom.randint(0, nw - w)
            canvas = np.empty((nh, nw, arr.shape[2]), arr.dtype)
            canvas[...] = np.asarray(self.pad_val, arr.dtype)
            canvas[y0:y0 + h, x0:x0 + w] = arr
            out = label.copy()
            valid = out[:, 0] >= 0
            out[valid, 1] = (out[valid, 1] * w + x0) / nw
            out[valid, 3] = (out[valid, 3] * w + x0) / nw
            out[valid, 2] = (out[valid, 2] * h + y0) / nh
            out[valid, 4] = (out[valid, 4] * h + y0) / nh
            return _like(src, canvas), out
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0., rand_mirror=False, mean=None,
                       std=None, brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmentation pipeline (reference
    CreateDetAugmenter): geometric det-augs + borrowed photometric augs
    + final forced resize to data_shape."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(1.0, area_range[0]), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.814],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is not None or std is not None:
        if mean is True:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True:
            std = np.array([58.395, 57.12, 57.375])
        auglist.append(DetBorrowAug(CastAug()))
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: batches (data, padded object labels).

    Labels parse from the im2rec detection header [A, B, ...extra,
    objects...]; every batch emits (batch, max_objects, object_width)
    padded with -1 (reference ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="label",
                 last_batch_handle="pad", **kwargs):
        # split kwargs: iterator options go to ImageIter, the rest are
        # detection-augmenter parameters
        parent_keys = ("part_index", "num_parts", "preprocess_threads")
        parent_kw = {k: kwargs.pop(k) for k in parent_keys if k in kwargs}
        super(ImageDetIter, self).__init__(
            batch_size=batch_size, data_shape=data_shape,
            path_imgrec=path_imgrec, path_imglist=path_imglist,
            path_root=path_root, path_imgidx=path_imgidx,
            shuffle=shuffle, aug_list=[] if aug_list is None else aug_list,
            imglist=imglist, data_name=data_name, label_name=label_name,
            last_batch_handle=last_batch_handle, **parent_kw)
        if aug_list is None:
            self.auglist = CreateDetAugmenter(data_shape, **kwargs)
        elif kwargs:
            raise TypeError(
                "unexpected keyword arguments with an explicit aug_list: "
                "%s" % sorted(kwargs))
        # scan labels once for (max_objects, object_width)
        max_obj, owidth = 1, 5
        for idx in self.seq:
            lab = self._raw_label(idx)
            parsed = self._parse_det_label(lab)
            max_obj = max(max_obj, parsed.shape[0])
            owidth = parsed.shape[1]
        self._max_objects = max_obj
        self._object_width = owidth
        self.provide_label = [DataDesc(
            label_name, (batch_size, max_obj, owidth), "float32")]

    def _label_batch_shape(self):
        return (self._max_objects, self._object_width)

    def _raw_label(self, idx):
        from . import recordio
        if self.imgrec is not None:
            header, _ = recordio.unpack(self.imgrec.read_idx(idx))
            return np.asarray(header.label, dtype=np.float32)
        return self.imglist[idx][0]

    @staticmethod
    def _parse_det_label(label):
        """[A, B, extra..., obj0[B]...] -> (num_obj, B) array; raw flat
        object lists (no header) fall back to width 5."""
        label = np.asarray(label, dtype=np.float32).ravel()
        if label.size >= 2 and 1 <= label[0] <= 16 and \
                2 <= label[1] <= 16:
            a, b = int(label[0]), int(label[1])
            body = label[a:]
        else:
            b = 5
            body = label
        n = body.size // b
        return body[:n * b].reshape(n, b).copy()

    def _decoded_sample(self):
        # decode via the shared (optionally threaded) prefetch path;
        # label parsing and the label-transforming det augmenters run on
        # the calling thread
        if self._cache:
            return self._cache.pop(0)
        label, arr = self._next_raw_decoded()
        img = arr if self._augs_np_fast() else nd.array(arr, dtype="uint8")
        parsed = self._parse_det_label(label)
        padded = np.full((self._max_objects, self._object_width), -1.0,
                         np.float32)
        padded[:len(parsed)] = parsed
        for aug in self.auglist:
            img, padded = aug(img, padded)
        return _as_np(img), padded

    def reshape(self, data_shape=None, label_shape=None):
        """Change batch shapes between bindings (reference reshape)."""
        if data_shape is not None:
            self.data_shape = data_shape
            self.provide_data = [DataDesc(
                self.provide_data[0].name,
                (self.batch_size,) + data_shape, "float32")]
        if label_shape is not None:
            self._max_objects, self._object_width = label_shape
            self.provide_label = [DataDesc(
                self.provide_label[0].name,
                (self.batch_size,) + tuple(label_shape), "float32")]

    def sync_label_shape(self, it, verbose=False):
        """Grow both iterators to the common max label shape (reference
        sync_label_shape, used to align train and val iterators)."""
        assert isinstance(it, ImageDetIter)
        mo = max(self._max_objects, it._max_objects)
        ow = max(self._object_width, it._object_width)
        self.reshape(label_shape=(mo, ow))
        it.reshape(label_shape=(mo, ow))
        return it
