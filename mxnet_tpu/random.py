"""Global RNG state.

Reference: python/mxnet/random.py (mx.random.seed) backed by per-device
Philox resource states (src/operator/random/). TPU-native: a functional
threefry key chain. Eager ops split from a host-held key; traced code
(CachedOp / executor / jitted train steps) pushes a *tracer* key onto the
stack so every dropout/sampler inside the trace derives from a key that is
a real input of the compiled computation — which is what keeps compiled
randomness fresh across calls instead of baked in as a constant.
"""

import threading

import jax

from ._discover import ensure_backend

_state = threading.local()


def _stack():
    if not hasattr(_state, "keys"):
        # PRNGKey is often a process's FIRST jax computation (e.g. its
        # first op is nd.random.*) — run the wedge guard before it
        ensure_backend()
        _state.keys = [jax.random.PRNGKey(0)]
    return _state.keys


def seed(seed_state, ctx="all"):
    """mx.random.seed (python/mxnet/random.py:38)."""
    ensure_backend()  # may be the first jax touch (wedge guard)
    _stack()[:] = [jax.random.PRNGKey(int(seed_state))]


def next_key():
    """Split a fresh subkey off the innermost key scope."""
    st = _stack()
    st[-1], sub = jax.random.split(st[-1])
    return sub


class key_scope:
    """Push an explicit (possibly traced) key for the duration of a trace."""

    def __init__(self, key):
        self.key = key

    def __enter__(self):
        _stack().append(self.key)
        return self

    def __exit__(self, *a):
        _stack().pop()


# Convenience samplers mirroring mx.random.* (python/mxnet/ndarray/random.py)
def _nd():
    from . import ndarray as nd
    return nd


def uniform(low=0, high=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _nd().random.uniform(low, high, shape=shape, dtype=dtype, ctx=ctx)


def normal(loc=0, scale=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _nd().random.normal(loc, scale, shape=shape, dtype=dtype, ctx=ctx)


def randn(*shape, **kw):
    return normal(shape=shape, **kw)


def poisson(lam=1, shape=(), dtype="float32", ctx=None, **kw):
    return _nd().random.poisson(lam, shape=shape, dtype=dtype, ctx=ctx)


def exponential(scale=1, shape=(), dtype="float32", ctx=None, **kw):
    return _nd().random.exponential(1.0 / scale, shape=shape, dtype=dtype, ctx=ctx)


def gamma(alpha=1, beta=1, shape=(), dtype="float32", ctx=None, **kw):
    return _nd().random.gamma(alpha, beta, shape=shape, dtype=dtype, ctx=ctx)


def negative_binomial(k=1, p=1, shape=(), dtype="float32", ctx=None, **kw):
    return _nd().random.negative_binomial(k, p, shape=shape, dtype=dtype, ctx=ctx)


def generalized_negative_binomial(mu=1, alpha=1, shape=(), dtype="float32",
                                  ctx=None, **kw):
    return _nd().random.generalized_negative_binomial(mu, alpha, shape=shape,
                                                      dtype=dtype, ctx=ctx)


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    return _nd().random.multinomial(data, shape=shape, get_prob=get_prob,
                                    dtype=dtype)


def randint(low, high, shape=(), dtype="int32", ctx=None, **kw):
    return _nd().random.randint(low, high, shape=shape, dtype=dtype, ctx=ctx)


def shuffle(data, **kw):
    return _nd().shuffle(data)
