"""Matrix / shape-manipulation / indexing operators.

Reference: src/operator/tensor/matrix_op.cc (+ matrix_op-inl.h), dot-inl.h,
indexing_op.cc, init_op.cc, ordering_op.cc, histogram.cc. All static-shape
transforms — exactly what XLA wants; `dot`/`batch_dot` land on the MXU via
lax.dot_general.
"""

import jax
import jax.numpy as jnp
from jax import lax

from . import register


# ------------------------------------------------------------------ dot --
@register(name="dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """src/operator/tensor/dot-inl.h — 2D (and nD-flattened) matmul."""
    a = lhs.T if transpose_a and lhs.ndim == 2 else (jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs)
    b = rhs.T if transpose_b and rhs.ndim == 2 else (jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot contracts last axis of a with first axis of b for nD
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register(name="batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


# ---------------------------------------------------------------- shape --
@register(name="Reshape", aliases=("reshape",))
def reshape(data, shape=(), reverse=False):
    """src/operator/tensor/matrix_op.cc Reshape with MXNet's special codes:
    0 copy dim, -1 infer, -2 copy rest, -3 merge two, -4 split."""
    if not shape:
        return data
    src = list(data.shape[::-1]) if reverse else list(data.shape)
    spec = list(shape[::-1]) if reverse else list(shape)
    out = []
    i = 0
    j = 0
    while j < len(spec):
        s = spec[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = spec[j + 1], spec[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(s)
            if i < len(src):
                i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return data.reshape(tuple(out))


@register(name="reshape_like")
def reshape_like(lhs, rhs):
    return lhs.reshape(rhs.shape)


@register(name="Flatten", aliases=("flatten",))
def flatten(data):
    return data.reshape(data.shape[0], -1)


@register(name="transpose")
def transpose(data, axes=None):
    if axes is None or axes == ():
        return jnp.transpose(data)
    return jnp.transpose(data, axes)


@register(name="expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register(name="squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis)


@register(name="swapaxes", aliases=("SwapAxis",))
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register(name="depth_to_space")
def depth_to_space(data, block_size=2):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return x.reshape(n, c // (b * b), h * b, w * b)


@register(name="space_to_depth")
def space_to_depth(data, block_size=2):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(n, c * b * b, h // b, w // b)


# ---------------------------------------------------------------- slice --
@register(name="slice", aliases=("crop",))
def slice_op(data, begin=(), end=(), step=()):
    idx = []
    for i in range(data.ndim):
        b = begin[i] if i < len(begin) else None
        e = end[i] if i < len(end) else None
        s = step[i] if i < len(step) and step[i] is not None and step[i] != 0 else None
        idx.append(slice(b, e, s))
    return data[tuple(idx)]


@register(name="slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    ax = axis % data.ndim
    idx = [slice(None)] * data.ndim
    idx[ax] = slice(begin, end)
    return data[tuple(idx)]


@register(name="slice_like")
def slice_like(data, shape_like, axes=()):
    axes = axes or tuple(range(min(data.ndim, shape_like.ndim)))
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a % data.ndim] = slice(0, shape_like.shape[a % shape_like.ndim])
    return data[tuple(idx)]


@register(name="SliceChannel", aliases=("split",), num_outputs="n")
def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    """src/operator/slice_channel.cc."""
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register(name="Concat", aliases=("concat",))
def concat(*data, dim=1):
    """src/operator/nn/concat.cc."""
    return jnp.concatenate(data, axis=dim)


@register(name="stack")
def stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@register(name="tile")
def tile(data, reps=()):
    return jnp.tile(data, reps)


@register(name="repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register(name="reverse", aliases=("flip",))
def reverse(data, axis=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(data, axis=axes)


@register(name="Pad", aliases=("pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    """src/operator/pad.cc — pad_width is MXNet's flat (before,after) pairs."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    while len(pw) < data.ndim:
        pw.append((0, 0))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


# ------------------------------------------------------------- indexing --
def _idx_dtype(dim):
    # int32 indices (TPU-native) unless the indexed axis exceeds int32
    # range — large-tensor support (ndarray._large_tensor_ctx)
    return "int64" if dim > 2**31 - 1 else "int32"


@register(name="take")
def take(a, indices, axis=0, mode="clip"):
    """src/operator/tensor/indexing_op.cc take."""
    idx = indices.astype(_idx_dtype(a.shape[axis]))
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    elif mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register(name="batch_take")
def batch_take(a, indices):
    idx = jnp.clip(indices.astype(_idx_dtype(a.shape[1])), 0,
                   a.shape[1] - 1)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register(name="Embedding")
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    """src/operator/tensor/indexing_op.cc Embedding — gather rows. On TPU a
    gather from HBM; sparse_grad collapses to dense (no sparse memory ops)."""
    idx = jnp.clip(data.astype("int32"), 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


@register(name="one_hot", differentiable=False)
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype("int32"), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register(name="gather_nd")
def gather_nd(data, indices):
    idx = indices.astype("int32")
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register(name="scatter_nd")
def scatter_nd(data, indices, shape=()):
    idx = indices.astype("int32")
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register(name="_scatter_set_nd")
def scatter_set_nd(lhs, indices, rhs, shape=()):
    idx = indices.astype("int32")
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(rhs)


@register(name="where")
def where(condition, x, y):
    return jnp.where(condition != 0 if condition.dtype != jnp.bool_ else condition, x, y)


@register(name="boolean_mask_dense")
def boolean_mask_dense(data, mask):
    """Static-shape companion of contrib boolean_mask: zeroes masked-out
    rows and keeps the input shape (usable under jit, unlike the
    compacted variant below)."""
    m = (mask != 0).astype(data.dtype)
    return data * m.reshape(m.shape + (1,) * (data.ndim - m.ndim))


@register(name="_contrib_boolean_mask")
def boolean_mask(data, index, axis=0):
    """contrib boolean_mask (src/operator/contrib/boolean_mask.cc):
    compacted rows where index != 0. The output shape depends on the
    DATA, so this op is eager-only — inside jit/symbolic tracing jax
    raises a concretization error (use boolean_mask_dense there)."""
    keep = jnp.asarray(index) != 0
    idx = jnp.nonzero(keep)[0]          # data-dependent: eager only
    return jnp.take(data, idx, axis=axis)


# ------------------------------------------------------------- ordering --
@register(name="sort")
def sort(data, axis=-1, is_ascend=True):
    r = jnp.sort(data, axis=axis)
    if not is_ascend:
        r = jnp.flip(r, axis=axis)
    return r


@register(name="argsort", differentiable=False)
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    r = jnp.argsort(data, axis=axis)
    if not is_ascend:
        r = jnp.flip(r, axis=axis)
    return r.astype(jnp.dtype(dtype))


@register(name="topk", differentiable=False, num_outputs="n")
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """src/operator/tensor/ordering_op.cc."""
    ax = axis % data.ndim if axis is not None else data.ndim - 1
    d = jnp.moveaxis(data, ax, -1)
    if is_ascend:
        vals, idxs = lax.top_k(-d, k)
        vals = -vals
    else:
        vals, idxs = lax.top_k(d, k)
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax).astype(jnp.dtype(dtype))
    if ret_typ == "indices":
        return idxs
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idxs
    if ret_typ == "mask":
        oh = jax.nn.one_hot(jnp.moveaxis(idxs, ax, -1).astype("int32"),
                            data.shape[ax], dtype=data.dtype).sum(-2)
        return jnp.moveaxis(oh, -1, ax)
    raise ValueError(ret_typ)


@register(name="shuffle", aliases=("_shuffle",),
          differentiable=False, stateful_rng=True)
def shuffle(data, rng_key=None):
    return jax.random.permutation(rng_key, data, axis=0)


# ----------------------------------------------------------------- init --
@register(name="_zeros", differentiable=False)
def zeros(shape=(), dtype="float32"):
    return jnp.zeros(shape, dtype=jnp.dtype(dtype))


@register(name="_ones", differentiable=False)
def ones(shape=(), dtype="float32"):
    return jnp.ones(shape, dtype=jnp.dtype(dtype))


@register(name="_full", differentiable=False)
def full(shape=(), value=0.0, dtype="float32"):
    return jnp.full(shape, value, dtype=jnp.dtype(dtype))


@register(name="_arange", differentiable=False)
def arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    r = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat > 1:
        r = jnp.repeat(r, repeat)
    return r


@register(name="_linspace", differentiable=False)
def linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    return jnp.linspace(start, stop, num, endpoint=endpoint, dtype=jnp.dtype(dtype))


@register(name="_eye", differentiable=False)
def eye(N=1, M=0, k=0, dtype="float32"):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=jnp.dtype(dtype))


@register(name="zeros_like", differentiable=False)
def zeros_like(data):
    return jnp.zeros_like(data)


@register(name="ones_like", differentiable=False)
def ones_like(data):
    return jnp.ones_like(data)


@register(name="shape_array", differentiable=False)
def shape_array(data):
    return jnp.asarray(data.shape, dtype="int64")


@register(name="size_array", differentiable=False)
def size_array(data):
    return jnp.asarray([data.size], dtype="int64")


@register(name="histogram", aliases=("_histogram",),
          differentiable=False, num_outputs=2)
def histogram(data, bins=10, range=None):
    cnt, edges = jnp.histogram(data, bins=bins, range=range)
    return cnt.astype("float32"), edges


@register(name="diag")
def diag(data, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)


@register(name="UpSampling")
def upsampling(*data, scale=1, sample_type="nearest", num_args=1, num_filter=0,
               multi_input_mode="concat"):
    """src/operator/nn/upsampling.cc (nearest only; bilinear uses the
    deconv path in the reference — here jax.image.resize)."""
    x = data[0]
    n, c, h, w = x.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    else:
        out = jax.image.resize(x, (n, c, h * scale, w * scale), method="bilinear")
    return out


@register(name="GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """src/operator/grid_generator.cc — affine only."""
    h, w = target_shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones_ = jnp.ones_like(gx)
    grid = jnp.stack([gx.ravel(), gy.ravel(), ones_.ravel()], axis=0)
    theta = data.reshape(-1, 2, 3)
    out = jnp.einsum("nij,jk->nik", theta, grid)
    return out.reshape(-1, 2, h, w)


@register(name="BilinearSampler")
def bilinear_sampler(data, grid, cudnn_off=False):
    """src/operator/bilinear_sampler.cc — sample NCHW `data` at `grid`
    locations in [-1,1]."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx); x1 = x0 + 1
    y0 = jnp.floor(gy); y1 = y0 + 1
    wx1 = gx - x0; wx0 = 1.0 - wx1
    wy1 = gy - y0; wy0 = 1.0 - wy1

    def sample(yy, xx):
        valid = (xx >= 0) & (xx <= w - 1) & (yy >= 0) & (yy <= h - 1)
        xc = jnp.clip(xx, 0, w - 1).astype("int32")
        yc = jnp.clip(yy, 0, h - 1).astype("int32")
        flat = data.reshape(n, c, h * w)
        lin = (yc * w + xc).reshape(n, -1)
        g = jnp.take_along_axis(flat, lin[:, None, :], axis=2)
        g = g.reshape(n, c, *xx.shape[1:])
        return g * valid[:, None].astype(data.dtype)

    out = (sample(y0, x0) * (wy0 * wx0)[:, None]
           + sample(y0, x1) * (wy0 * wx1)[:, None]
           + sample(y1, x0) * (wy1 * wx0)[:, None]
           + sample(y1, x1) * (wy1 * wx1)[:, None])
    return out


# ------------------------------------------------------------ sequence --
@register(name="SequenceMask")
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    """src/operator/sequence_mask.cc — data is (seq, batch, ...) for axis=0."""
    if not use_sequence_length or sequence_length is None:
        return data
    seq_axis = axis
    slen = data.shape[seq_axis]
    pos = jnp.arange(slen)
    shape = [1] * data.ndim
    shape[seq_axis] = slen
    pos = pos.reshape(shape)
    batch_axis = 1 - seq_axis
    lshape = [1] * data.ndim
    lshape[batch_axis] = data.shape[batch_axis]
    lens = sequence_length.reshape(lshape)
    mask = pos < lens
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register(name="SequenceLast")
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    lens = jnp.clip(sequence_length.astype("int32") - 1, 0, data.shape[axis] - 1)
    d = jnp.moveaxis(data, axis, 0)
    return jnp.take_along_axis(
        d, lens.reshape((1, -1) + (1,) * (d.ndim - 2)), axis=0)[0]


@register(name="SequenceReverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    slen = data.shape[axis]
    pos = jnp.arange(slen)[:, None]
    lens = sequence_length.astype("int32")[None, :]
    rev_idx = jnp.where(pos < lens, lens - 1 - pos, pos)
    d = jnp.moveaxis(data, axis, 0)
    out = jnp.take_along_axis(d, rev_idx.reshape(rev_idx.shape + (1,) * (d.ndim - 2)), axis=0)
    return jnp.moveaxis(out, 0, axis)


# ------------------------------------------------- reference-parity ops --
@register(name="_split_v2", num_outputs="n")
def split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0):
    """src/operator/tensor/matrix_op.cc `_split_v2` — split by equal
    sections, or at explicit section-START boundaries: `indices` includes
    the leading 0 (the python `split_v2` wrapper prepends it), and output
    i spans [indices[i], indices[i+1]) — so len(indices) outputs."""
    ax = axis % data.ndim
    if sections and sections > 0:
        parts = jnp.split(data, sections, axis=axis)
    else:
        bounds = list(indices) + [data.shape[ax]]
        parts = [jax.lax.slice_in_dim(data, bounds[i], bounds[i + 1], axis=ax)
                 for i in range(len(indices))]
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


def _region(begin, end, step, shape):
    idx = []
    for i in range(len(shape)):
        b = begin[i] if i < len(begin) else None
        e = end[i] if i < len(end) else None
        s = step[i] if i < len(step) and step[i] not in (None, 0) else None
        idx.append(slice(b, e, s))
    return tuple(idx)


@register(name="_slice_assign", aliases=("_crop_assign",))
def slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """`x[begin:end:step] = y` as a pure op (matrix_op.cc `_slice_assign`)."""
    return lhs.at[_region(begin, end, step, lhs.shape)].set(rhs)


@register(name="_slice_assign_scalar", aliases=("_crop_assign_scalar",))
def slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    return data.at[_region(begin, end, step, data.shape)].set(
        jnp.asarray(scalar, data.dtype))


@register(name="_ravel_multi_index", aliases=("ravel_multi_index",),
          differentiable=False)
def ravel_multi_index(data, shape=()):
    """src/operator/tensor/ravel.cc — data is (ndim, N) coordinates."""
    strides = []
    acc = 1
    for d in reversed(shape):
        strides.append(acc)
        acc *= d
    strides = jnp.asarray(strides[::-1], data.dtype).reshape(-1, 1)
    return jnp.sum(data * strides, axis=0)


@register(name="_unravel_index", aliases=("unravel_index",),
          differentiable=False)
def unravel_index(data, shape=()):
    coords = jnp.unravel_index(data, shape)
    return jnp.stack([c.astype(data.dtype) for c in coords], axis=0)


@register(name="_identity_with_attr_like_rhs")
def identity_with_attr_like_rhs(lhs, rhs):
    """Internal reference op (tensor/elemwise_unary_op_basic.cc) used by
    sparse gradient graphs: forwards lhs, rhs only pins shape/stype."""
    return lhs


@register(name="_zeros_without_dtype", differentiable=False)
def zeros_without_dtype(shape=(), ctx=None, dtype=None):
    return jnp.zeros(tuple(shape) if not isinstance(shape, int) else (shape,),
                     jnp.dtype(dtype) if dtype not in (None, -1) else jnp.float32)


@register(name="_rnn_param_concat")
def rnn_param_concat(*data, dim=0, num_args=None):
    """tensor/matrix_op.cc `_rnn_param_concat` — 1-D parameter pack concat
    used when fusing per-gate RNN weights into the packed layout."""
    return jnp.concatenate([d.reshape(-1) if d.ndim != 1 else d for d in data],
                           axis=0)


@register(name="cast_storage", differentiable=False)
def cast_storage_op(data, stype="default"):
    """ndarray-level storage casts happen in mxnet_tpu.sparse (host-side
    wrappers); inside a graph every array is dense on TPU, so the op is
    the identity (documented divergence, SURVEY §7 hard part (a))."""
    return data


@register(name="_sparse_retain")
def sparse_retain(data, indices):
    """sparse_retain dense emulation: keep the listed rows, zero the rest
    (reference semantics on row_sparse restricted to a dense layout)."""
    keep = jnp.zeros((data.shape[0],), bool).at[indices.astype("int32")].set(True)
    return jnp.where(keep.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)
