"""Neural-network operators.

Reference: src/operator/nn/ (Convolution, Pooling, FullyConnected, BatchNorm,
LayerNorm, GroupNorm, LRN, Activation, Dropout, softmax family, CTCLoss,
Upsampling), src/operator/rnn.cc (fused RNN), src/operator/leaky_relu.cc,
src/operator/softmax_output.cc, src/operator/instance_norm.cc.

TPU-native mapping: convs/matmuls are lax.conv_general_dilated/dot_general on
the MXU (bf16-friendly); max pooling is native lax.reduce_window with XLA's
select-and-scatter backward (first-max ties, the reference convention);
avg/sum/lp pooling is a strided-slice window accumulation; the fused RNN is a
lax.scan over time steps (XLA pipelines the per-step matmuls); there are no
cuDNN/MKLDNN forks — one implementation, every backend.
"""

import functools

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from . import register
from .. import _fastenv as _fe


def _tuplize(v, n):
    if v is None or v == ():
        return (1,) * n if n else ()
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


# ---------------------------------------------------------- convolution --
def _conv_dnums(nd):
    # MXNet default layouts: NCW / NCHW / NCDHW, weights OIHW-style
    spatial = "DHW"[-nd:] if nd <= 3 else None
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    return lax.conv_dimension_numbers((0,) * (nd + 2), (0,) * (nd + 2),
                                      (lhs, rhs, lhs))


def _conv_core(data, weight, stride, dilate, pad, num_group):
    # bf16 convs: no preferred_element_type — the MXU already accumulates
    # bf16 products in fp32, and forcing an fp32 output dtype breaks the
    # conv transpose rule (fp32 cotangent meets bf16 operand in the
    # weight-gradient conv)
    return lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=_conv_dnums(data.ndim - 2),
        feature_group_count=num_group)


def _int8_residual_enabled():
    # OPT-IN (lossy): MXNET_INT8_RESIDUAL=1 saves each conv's input
    # activation as symmetric per-channel int8 (plus an fp32 scale) for
    # the weight-gradient conv — halving the largest residual class of
    # an AMP ResNet step at a ~1e-2 relative dW error (dX stays exact:
    # it only needs the weights). This is PERF.md's "8-bit
    # saved-activation compression" intensity lever; default OFF
    # because it changes training numerics.
    import os
    return os.environ.get("MXNET_INT8_RESIDUAL", "0").lower() in (
        "1", "true")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _conv_int8_residual(data, weight, stride, dilate, pad, num_group):
    return _conv_core(data, weight, stride, dilate, pad, num_group)


def _conv_i8_fwd(data, weight, stride, dilate, pad, num_group):
    out = _conv_core(data, weight, stride, dilate, pad, num_group)
    red = tuple(i for i in range(data.ndim) if i != 1)
    amax = jnp.max(jnp.abs(data.astype(jnp.float32)), axis=red,
                   keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(data.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    return out, (q, scale, weight)


def _conv_i8_bwd(stride, dilate, pad, num_group, res, ct):
    q, scale, weight = res
    deq = (q.astype(jnp.float32) * scale).astype(weight.dtype)
    # conv is bilinear: its transpose evaluated at the dequantized
    # input gives dW from the int8 reconstruction (lossy) and dX from
    # the exact weights
    _, vjp = jax.vjp(
        lambda d, w: _conv_core(d, w, stride, dilate, pad, num_group),
        deq, weight)
    return vjp(ct)


_conv_int8_residual.defvjp(_conv_i8_fwd, _conv_i8_bwd)


@register(name="Convolution")
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=1, num_group=1, no_bias=False,
                layout=None, workspace=1024, cudnn_tune=None, cudnn_off=False):
    """src/operator/nn/convolution.cc — N-D convolution, NC[DHW] layout.

    `workspace`/`cudnn_*` are accepted for source compat and ignored (XLA
    picks MXU tilings; there is no algo autotune registry to manage —
    reference kept one in src/operator/nn/cudnn/cudnn_algoreg-inl.h).
    """
    nd = data.ndim - 2
    stride = _tuplize(stride, nd)
    dilate = _tuplize(dilate, nd)
    pad = _tuplize(pad if pad != () else 0, nd)
    if _int8_residual_enabled():
        out = _conv_int8_residual(data, weight, stride, dilate, pad,
                                  num_group)
    else:
        out = _conv_core(data, weight, stride, dilate, pad, num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register(name="Deconvolution")
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), target_shape=(), num_filter=1, num_group=1,
                  no_bias=True, layout=None, workspace=1024, cudnn_tune=None,
                  cudnn_off=False):
    """src/operator/nn/deconvolution.cc — transposed conv (gradient of conv
    w.r.t. its input, lowered via lax.conv_transpose semantics)."""
    nd = data.ndim - 2
    stride = _tuplize(stride, nd)
    dilate = _tuplize(dilate, nd)
    pad = _tuplize(pad if pad != () else 0, nd)
    adj = _tuplize(adj if adj != () else 0, nd)
    dn = _conv_dnums(nd)
    kshape = weight.shape[2:]
    if target_shape not in ((), None) and any(target_shape):
        # reference semantics (deconvolution-inl.h InferPad): a given
        # target_shape DISCARDS user pad/adj and derives both — the
        # zero-pad natural output stride*(in-1)+k_dilated must be >=
        # target ("too big target shape" otherwise); the excess splits
        # into pad = ceil(excess/2), adj = excess % 2, which lands the
        # output exactly on target.
        target_shape = _tuplize(target_shape, nd)
        pad, adj = [], []
        for i in range(nd):
            k = (kshape[i] - 1) * dilate[i] + 1
            natural = (data.shape[2 + i] - 1) * stride[i] + k
            if int(target_shape[i]) > natural:
                raise ValueError(
                    "too big target shape: target_shape[%d]=%d exceeds "
                    "the zero-pad output %d (= stride*(in-1) + "
                    "dilated_kernel)" % (i, target_shape[i], natural))
            excess = natural - int(target_shape[i])
            adj.append(excess % 2)
            pad.append((excess + 1) // 2)
        pad, adj = tuple(pad), tuple(adj)
    # transposed conv = lhs-dilated conv with flipped kernel, swapped I/O
    pads = []
    for i in range(nd):
        k = (kshape[i] - 1) * dilate[i] + 1
        lo = k - 1 - pad[i]
        hi = k - 1 - pad[i] + adj[i]
        pads.append((lo, hi))
    if num_group > 1:
        ws = weight.shape
        w = weight.reshape(num_group, ws[0] // num_group, ws[1], *kshape)
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape(ws[1] * num_group, ws[0] // num_group, *kshape)
    else:
        w = jnp.swapaxes(weight, 0, 1)
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# -------------------------------------------------------------- pooling --
def _window_reduce(data, kernel, stride, pads, combine, init_val, use_np=False):
    """Reduce over sliding windows via one strided slice per kernel offset.

    `data` is NC<spatial> (or bare <spatial> with use_np=True for static
    count computation). `pads` is [(lo, hi)] per spatial dim."""
    import itertools
    xp = _np if use_np else jnp
    nsp = len(kernel)
    nbatch = data.ndim - nsp
    pad_cfg = [(0, 0)] * nbatch + list(pads)
    if use_np:
        padded = _np.pad(data, pad_cfg, constant_values=init_val)
    else:
        padded = jnp.pad(data, pad_cfg, constant_values=init_val)
    out_len = [(padded.shape[nbatch + d] - kernel[d]) // stride[d] + 1
               for d in range(nsp)]
    acc = None
    for off in itertools.product(*[range(k) for k in kernel]):
        starts = [0] * nbatch + list(off)
        limits = list(padded.shape[:nbatch]) + \
            [off[d] + (out_len[d] - 1) * stride[d] + 1 for d in range(nsp)]
        strides = [1] * nbatch + list(stride)
        if use_np:
            sl = tuple(slice(s, l, st)
                       for s, l, st in zip(starts, limits, strides))
            piece = padded[sl]
        else:
            piece = lax.slice(padded, starts, limits, strides)
        acc = piece if acc is None else combine(acc, piece)
    return acc


_KNOB_CACHE = (None, None)      # (raw strings, parsed bools) — one
# tuple so readers always see a matching pair (atomic publish)


def residual_knobs():
    """The trace-time residual-format flags as one tuple. Compiled-fn
    caches (CachedOp._get_fn, the eager record-vjp cache) include it in
    their keys so toggling an env knob in-process retraces instead of
    silently reusing a stale program (the MXNET_BACKWARD_DO_MIRROR
    cache-aliasing class). Executor latches them at bind time, like
    mirror.

    Called on EVERY recorded eager dispatch, so the parse is memoized
    against the raw env strings — ~0.5 us instead of ~4 (the dispatch
    ladder budget is ~10 us/op, benchmark/opperf.py --dispatch)."""
    global _KNOB_CACHE
    raw = (_fe.get("MXNET_INT8_RESIDUAL"),
           _fe.get("MXNET_BN_BF16_RESIDUAL"),
           _fe.get("MXNET_RELU_MASK_RESIDUAL"),
           _fe.get("MXNET_POOL_INDEX_RESIDUAL"))
    cached = _KNOB_CACHE
    if raw == cached[0]:
        return cached[1]

    def flag(v, default):
        # parse the strings we ALREADY read: same rule as the
        # _*_enabled() trace-site readers, without re-reading env
        # (which would reopen the raw/parsed mismatch window)
        return (v if v is not None else default).lower() in ("1", "true")

    parsed = (flag(raw[0], "0"), flag(raw[1], "1"),
              flag(raw[2], "1"), flag(raw[3], "1"))
    _KNOB_CACHE = (raw, parsed)
    return parsed


def _pool_index_residual():
    import os
    # default OFF since the round-5 HLO diff (benchmark/hlo_diff.py):
    # the index path's stacked-window forward materializes a K-times
    # activation buffer and its backward runs K sequential full-buffer
    # scatter-adds — on chip that was most of the 10 GB/step gap
    # between the shipped ResNet step (56.2 GB, 2187 img/s) and the
    # hand-built step (45.8 GB, 2461 img/s) in the same session
    # (BENCH_TABLE cost_compare_timed). The native lax.reduce_window
    # path lowers to one fused window reduce + select-and-scatter and
    # carries the SAME first-max tie convention the reference uses
    # (mshadow pooling; verified: gradient of an all-equal window lands
    # entirely on the first position), so the semantic argument that
    # originally motivated the index path holds natively.
    # MXNET_POOL_INDEX_RESIDUAL=1 re-enables the 1-byte-index variant
    # (its residual is smaller; useful when memory capacity, not
    # bandwidth, binds).
    return os.environ.get("MXNET_POOL_INDEX_RESIDUAL", "0").lower() in (
        "1", "true")


def _max_windows(data, kernel, stride, pads, init_val):
    """All kernel-offset strided slices stacked on a leading K axis."""
    import itertools
    nsp = len(kernel)
    nbatch = data.ndim - nsp
    pad_cfg = [(0, 0)] * nbatch + list(pads)
    padded = jnp.pad(data, pad_cfg, constant_values=init_val)
    out_len = [(padded.shape[nbatch + d] - kernel[d]) // stride[d] + 1
               for d in range(nsp)]
    pieces = []
    offsets = list(itertools.product(*[range(k) for k in kernel]))
    for off in offsets:
        starts = [0] * nbatch + list(off)
        limits = list(padded.shape[:nbatch]) + \
            [off[d] + (out_len[d] - 1) * stride[d] + 1 for d in range(nsp)]
        strides = [1] * nbatch + list(stride)
        pieces.append(lax.slice(padded, starts, limits, strides))
    return jnp.stack(pieces), offsets, padded.shape, out_len


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _maxpool_index(data, kernel, stride, pads, in_shape, dtype_name):
    out, _ = _maxpool_index_fwd(data, kernel, stride, pads, in_shape,
                                dtype_name)
    return out


def _maxpool_index_fwd(data, kernel, stride, pads, in_shape, dtype_name):
    init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
        else jnp.iinfo(data.dtype).min
    win, _, padded_shape, _ = _max_windows(data, kernel, stride, pads,
                                           init)
    # narrowest index type that can hold every window offset (a uint8
    # would silently WRAP for kernels with >256 elements, scattering
    # gradients to wrong positions)
    n_off = 1
    for kd in kernel:
        n_off *= kd
    idx_dt = jnp.uint8 if n_off <= 256 else (
        jnp.uint16 if n_off <= 65536 else jnp.int32)
    idx = jnp.argmax(win, axis=0).astype(idx_dt)      # first max wins
    out = jnp.max(win, axis=0)
    return out, idx


def _maxpool_index_bwd(kernel, stride, pads, in_shape, dtype_name, res,
                       ct):
    import itertools
    idx = res
    in_dtype = jnp.dtype(dtype_name)
    nsp = len(kernel)
    nbatch = len(in_shape) - nsp
    pad_cfg = [(0, 0)] * nbatch + list(pads)
    padded_shape = list(in_shape)
    for d in range(nsp):
        padded_shape[nbatch + d] += pads[d][0] + pads[d][1]
    g = jnp.zeros(padded_shape, jnp.float32)
    ct32 = ct.astype(jnp.float32)
    out_len = list(ct.shape[nbatch:])
    for k, off in enumerate(
            itertools.product(*[range(kd) for kd in kernel])):
        contrib = jnp.where(idx == k, ct32, 0.0)
        starts = [0] * nbatch + list(off)
        limits = list(padded_shape[:nbatch]) + \
            [off[d] + (out_len[d] - 1) * stride[d] + 1 for d in range(nsp)]
        strides = [1] * nbatch + list(stride)
        # transpose of lax.slice: scatter-add the contribution back
        g = g.at[tuple(
            slice(starts[i], limits[i], strides[i])
            for i in range(len(padded_shape)))].add(contrib)
    # un-pad
    unpad = tuple(slice(pad_cfg[i][0],
                        g.shape[i] - pad_cfg[i][1] or None)
                  for i in range(len(padded_shape)))
    g = g[unpad]
    return (g.astype(in_dtype),)


_maxpool_index.defvjp(_maxpool_index_fwd, _maxpool_index_bwd)


@register(name="Pooling")
def pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
            global_pool=False, pooling_convention="valid", cudnn_off=False,
            count_include_pad=True, layout=None, p_value=2):
    """src/operator/nn/pooling.cc — max/avg/sum/lp, valid/full conventions."""
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            out = jnp.max(data, axis=axes, keepdims=True)
        elif pool_type == "sum":
            out = jnp.sum(data, axis=axes, keepdims=True)
        elif pool_type == "lp":
            out = jnp.power(jnp.sum(jnp.power(jnp.abs(data), p_value), axis=axes,
                                    keepdims=True), 1.0 / p_value)
        else:
            out = jnp.mean(data, axis=axes, keepdims=True)
        return out
    kernel = _tuplize(kernel, nd)
    stride = _tuplize(stride, nd)
    pad = _tuplize(pad if pad != () else 0, nd)
    for i in range(nd):
        if pooling_convention != "full" and \
                kernel[i] > data.shape[2 + i] + 2 * pad[i]:
            raise ValueError(
                "Pooling kernel %s exceeds padded input %s on axis %d "
                "(valid convention); shrink the kernel, pad, or use "
                "global_pool" % (kernel, data.shape[2:], i))

    pads = []
    for i in range(nd):
        lo = hi = pad[i]
        if pooling_convention == "full":
            # ceil convention (pooling-inl.h): pad extra on the high side
            size = data.shape[2 + i] + 2 * pad[i] - kernel[i]
            rem = size % stride[i]
            if rem != 0:
                hi += stride[i] - rem
        pads.append((lo, hi))

    if pool_type == "max":
        # Opt-in 1-byte-index residual variant (capacity lever; see
        # _pool_index_residual for the chip evidence that retired it
        # as the default).
        if _pool_index_residual():
            return _maxpool_index(data, tuple(kernel), tuple(stride),
                                  tuple(tuple(p) for p in pads),
                                  tuple(data.shape), str(data.dtype))
        # Native windowed max: one fused reduce-window forward, XLA
        # select-and-scatter backward that assigns each window's
        # gradient to its FIRST max (the reference's mshadow tie
        # convention — all-equal windows, common after relu, send the
        # whole cotangent to position 0, not a 1/K split). It also
        # linearizes (jax.linearize / double-grad verified), so vjp
        # over jitted CachedOp graphs works. The init value must be a
        # PYTHON literal: jax only dispatches to the differentiable
        # reduce_window_max primitive when it recognizes the monoid
        # identity; a concrete device array falls back to the generic
        # reduce_window primitive, which has no autodiff rule.
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else int(jnp.iinfo(data.dtype).min)
        nbatch = data.ndim - nd
        return lax.reduce_window(
            data, init, lax.max,
            (1,) * nbatch + tuple(kernel),
            (1,) * nbatch + tuple(stride),
            [(0, 0)] * nbatch + [tuple(p) for p in pads])
    if pool_type == "lp":
        s = _window_reduce(jnp.power(jnp.abs(data), p_value), kernel, stride,
                           pads, jnp.add, 0)
        return jnp.power(s, 1.0 / p_value)
    s = _window_reduce(data, kernel, stride, pads, jnp.add, 0)
    if pool_type == "sum":
        return s
    # avg
    if count_include_pad:
        denom = float(_np.prod(kernel))
        return s / jnp.asarray(denom, data.dtype)
    # denominators depend only on static shapes — computed host-side
    cnt = _window_reduce(_np.ones(data.shape[2:], dtype=_np.float32),
                         kernel, stride, pads, _np.add, 0, use_np=True)
    return s / jnp.asarray(cnt, data.dtype)


# ------------------------------------------------------------- fully-connected --
@register(name="FullyConnected")
def fully_connected(data, weight, bias=None, num_hidden=1, no_bias=False,
                    flatten=True):
    """src/operator/nn/fully_connected.cc — y = x W^T + b on the MXU."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    y = jnp.matmul(x, weight.T)
    if not no_bias and bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------- norms --
@register(name="BatchNorm", aliases=("BatchNorm_v1",), num_outputs=3)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, is_train=False):
    """src/operator/nn/batch_norm.cc.

    Functional formulation: returns (out, batch_mean, batch_var); the caller
    (gluon.nn.BatchNorm / executor aux-state machinery) folds the running
    stats update `moving = momentum*moving + (1-m)*batch` — the reference op
    mutates its aux states in-place instead (batch_norm.cc:~400).
    """
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if is_train and not use_global_stats:
        # Single fused pass over the activation stream: E[x-s] and
        # E[(x-s)^2] accumulate in fp32 together (one reduction kernel,
        # often folded into the producing conv's epilogue), instead of the
        # mean-then-var two-pass formulation which re-reads `data` — BN is
        # HBM-bound on TPU, so the extra pass is ~40% of ResNet step time.
        # The shift s = running mean keeps the E[y^2]-E[y]^2 algebra
        # well-conditioned: raw E[x^2]-E[x]^2 cancels catastrophically in
        # fp32 when |mean| >> std, and the running mean tracks the batch
        # mean after the first few updates, making y near zero-mean.
        stat_shape = [1] * data.ndim
        stat_shape[ax] = data.shape[ax]
        shift = lax.stop_gradient(
            moving_mean.astype(jnp.float32)).reshape(stat_shape)
        if _bn_bf16_residual() and data.dtype == jnp.bfloat16:
            # keep `centered` in the ACTIVATION dtype: the backward
            # saves it as a residual on every BN input, and the fp32
            # form pins 2x the bf16 bytes (PERF.md ~22 GB/step suspect;
            # benchmark/bn_residual_ab.py + activation_residual_ab.py).
            # The reductions still accumulate in fp32.
            centered = data - shift.astype(data.dtype)
            mean_c = jnp.mean(centered, axis=red, dtype=jnp.float32)
            var = jnp.maximum(
                jnp.mean(centered * centered, axis=red,
                         dtype=jnp.float32) - mean_c * mean_c, 0.0)
        else:
            centered = data.astype(jnp.float32) - shift
            mean_c = jnp.mean(centered, axis=red)
            var = jnp.maximum(
                jnp.mean(centered * centered, axis=red)
                - mean_c * mean_c, 0.0)
        mean = (mean_c + shift.reshape(-1)).astype(moving_mean.dtype)
        var = var.astype(moving_var.dtype)
    else:
        mean, var = moving_mean, moving_var
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    # Precompute per-channel scale/bias in fp32 (tiny), then apply as one
    # fused multiply-add in the activation dtype: out = x*scale + bias.
    # AMP keeps norm params fp32; the bf16 stream is never upcast in HBM.
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = (g.astype(jnp.float32) * inv).astype(data.dtype)
    bias = (beta.astype(jnp.float32)
            - g.astype(jnp.float32) * mean.astype(jnp.float32) * inv
            ).astype(data.dtype)
    out = data * scale.reshape(shape) + bias.reshape(shape)
    return out.astype(data.dtype), mean, var


@register(name="_contrib_SyncBatchNorm", num_outputs=3)
def sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                    momentum=0.9, fix_gamma=True, use_global_stats=False,
                    output_mean_var=False, ndev=1, key="", is_train=False):
    """src/operator/contrib/sync_batch_norm.cc — cross-device BN.

    TPU-native: under GSPMD the batch axis is a global array dimension,
    so BatchNorm's reduction already spans every device (XLA inserts the
    psum over the data-parallel axis). The op therefore shares the
    BatchNorm kernel — including its (out, mean, var) contract so the
    executor folds the running-stat update identically. ndev/key are
    accepted for signature parity; the engine-barrier machinery they
    configured has no analogue here.
    """
    return batch_norm(
        data, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats,
        output_mean_var=output_mean_var, is_train=is_train)


@register(name="LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """src/operator/nn/layer_norm.cc."""
    ax = axis % data.ndim
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    xhat = (data - mean) * lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    return xhat * gamma.reshape(shape) + beta.reshape(shape)


@register(name="GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5, output_mean_var=False):
    """src/operator/nn/group_norm.cc — NC... input, groups over C."""
    n, c = data.shape[:2]
    rest = data.shape[2:]
    x = data.reshape(n, num_groups, c // num_groups, *rest)
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    xhat = ((x - mean) * lax.rsqrt(var + eps)).reshape(data.shape)
    shape = (1, c) + (1,) * len(rest)
    return xhat * gamma.reshape(shape) + beta.reshape(shape)


@register(name="InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    """src/operator/instance_norm.cc."""
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    xhat = (data - mean) * lax.rsqrt(var + eps)
    shape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return xhat * gamma.reshape(shape) + beta.reshape(shape)


@register(name="LRN")
def lrn(data, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    """src/operator/nn/lrn.cc — cross-channel local response norm."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    c = data.shape[1]
    s = None
    for off in range(nsize):  # channel-window sum as shifted slices
        piece = lax.slice_in_dim(padded, off, off + c, axis=1)
        s = piece if s is None else s + piece
    return data / jnp.power(knorm + alpha / nsize * s, beta)


def _bn_bf16_residual():
    # default ON: for bf16 activation streams the bf16-centered form
    # halves the BN backward residual (measured -19% of total step
    # residual bytes, benchmark/activation_residual_ab.py) with fp32
    # accumulation for the statistics; MXNET_BN_BF16_RESIDUAL=0 reverts
    # to fp32-centered residuals (the round-2 formulation). fp32
    # activation streams are numerically identical either way.
    import os
    return os.environ.get("MXNET_BN_BF16_RESIDUAL", "1").lower() in (
        "1", "true")


# ----------------------------------------------------------- activation --
@jax.custom_vjp
def _relu_mask_residual(x):
    return jnp.maximum(x, 0)


def _relu_mr_fwd(x):
    # save the SIGN MASK (1 byte/elem) instead of the activation
    # (2-4 bytes/elem): relu backward needs only where(x > 0). This is
    # the "8-bit activation compression for backward" lever from
    # PERF.md. Subgradient at x == 0 is 0 (the torch/standard
    # convention) whereas jnp.maximum's tie rule gives 0.5 — a
    # measure-zero divergence between the two paths, both valid
    # subgradients.
    return jnp.maximum(x, 0), x > 0


def _relu_mr_bwd(mask, ct):
    return (jnp.where(mask, ct, jnp.zeros_like(ct)),)


_relu_mask_residual.defvjp(_relu_mr_fwd, _relu_mr_bwd)


def _relu_mask_enabled():
    # default ON: the saved residual is a 1-byte sign mask instead of
    # the bf16 activation (-11% of ResNet step residual bytes,
    # benchmark/activation_residual_ab.py), and the subgradient at
    # x == 0 is 0 — the REFERENCE convention (mshadow_op.h relu_grad:
    # a > 0 ? 1 : 0) and torch's, vs jnp.maximum's 0.5 tie split.
    # MXNET_RELU_MASK_RESIDUAL=0 reverts.
    import os
    return os.environ.get("MXNET_RELU_MASK_RESIDUAL", "1").lower() in (
        "1", "true")


@register(name="Activation")
def activation(data, act_type="relu"):
    """src/operator/nn/activation.cc."""
    if act_type == "relu":
        if _relu_mask_enabled():
            return _relu_mask_residual(data)
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return lax.logistic(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jnp.logaddexp(data, 0.0)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise ValueError("unknown act_type %s" % act_type)


@register(name="LeakyReLU", stateful_rng=True)
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, rng_key=None,
               is_train=False):
    """src/operator/leaky_relu.cc — leaky/prelu/elu/selu/gelu/rrelu."""
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim:
            shape = [1] * data.ndim
            if g.size > 1 and data.ndim > 1:
                shape[1] = g.size
            g = g.reshape(shape)
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        a, s = 1.6732632423543772, 1.0507009873554805
        return s * jnp.where(data >= 0, data, a * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        if is_train and rng_key is not None:
            r = jax.random.uniform(rng_key, data.shape, dtype=data.dtype,
                                   minval=lower_bound, maxval=upper_bound)
        else:
            r = jnp.asarray((lower_bound + upper_bound) / 2.0, data.dtype)
        return jnp.where(data >= 0, data, r * data)
    raise ValueError("unknown act_type %s" % act_type)


# -------------------------------------------------------------- softmax --
@register(name="softmax")
def softmax(data, axis=-1, temperature=None, length=None, use_length=False,
            dtype=None):
    """src/operator/nn/softmax.cc."""
    x = data / temperature if temperature not in (None, 1.0, 0.0) else data
    if use_length and length is not None:
        pos = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis % x.ndim] = x.shape[axis]
        mask = pos.reshape(shape) < length.reshape([-1] + [1] * (x.ndim - 1))
        x = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    if dtype is not None:
        out = out.astype(jnp.dtype(dtype))
    return out


@register(name="log_softmax")
def log_softmax(data, axis=-1, temperature=None, dtype=None):
    x = data / temperature if temperature not in (None, 1.0, 0.0) else data
    out = jax.nn.log_softmax(x, axis=axis)
    if dtype is not None:
        out = out.astype(jnp.dtype(dtype))
    return out


@register(name="softmin")
def softmin(data, axis=-1, temperature=None, dtype=None):
    return softmax(-data, axis=axis, temperature=temperature, dtype=dtype)


@register(name="SoftmaxActivation")
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, multi_output,
                        use_ignore, preserve_shape, normalization, smooth_alpha):
    axis = 1 if (multi_output and data.ndim > 2) else -1
    return jax.nn.softmax(data, axis=axis)


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8))
def _softmax_output(data, label, grad_scale, ignore_label, multi_output,
                    use_ignore, preserve_shape, normalization, smooth_alpha):
    return _softmax_output_fwd(data, label, grad_scale, ignore_label,
                               multi_output, use_ignore, preserve_shape,
                               normalization, smooth_alpha)


def _so_fwd(data, label, grad_scale, ignore_label, multi_output, use_ignore,
            preserve_shape, normalization, smooth_alpha):
    out = _softmax_output_fwd(data, label, grad_scale, ignore_label,
                              multi_output, use_ignore, preserve_shape,
                              normalization, smooth_alpha)
    return out, (out, label)


def _so_bwd(grad_scale, ignore_label, multi_output, use_ignore,
            preserve_shape, normalization, smooth_alpha, res, g):
    out, label = res
    axis = 1 if (multi_output and out.ndim > 2) else -1
    nclass = out.shape[axis]
    lbl = label.astype("int32")
    oh = jax.nn.one_hot(lbl, nclass, axis=axis, dtype=out.dtype)
    if smooth_alpha:
        oh = oh * (1.0 - smooth_alpha - smooth_alpha / (nclass - 1)) \
            + smooth_alpha / (nclass - 1)
    grad = out - oh
    if use_ignore:
        keep = (lbl != int(ignore_label)).astype(out.dtype)
        keep = jnp.expand_dims(keep, axis % out.ndim)
        grad = grad * keep
    scale = grad_scale
    if normalization == "batch":
        scale = scale / out.shape[0]
    elif normalization == "valid" and use_ignore:
        valid = jnp.maximum(jnp.sum((lbl != int(ignore_label)).astype(out.dtype)), 1.0)
        grad = grad / valid
    grad = grad * scale
    return grad, jnp.zeros_like(label)


_softmax_output.defvjp(_so_fwd, _so_bwd)


@register(name="SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """src/operator/softmax_output.cc — softmax fwd; bwd is (p - onehot)
    (the classic fused softmax+CE gradient), via jax.custom_vjp."""
    lbl = label if jnp.issubdtype(label.dtype, jnp.floating) else label.astype("float32")
    return _softmax_output(data, lbl, grad_scale, ignore_label, multi_output,
                           use_ignore, preserve_shape, normalization, smooth_alpha)


@register(name="softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """src/operator/loss_binary_op.cc — summed CE over the batch."""
    logp = jax.nn.log_softmax(data, axis=-1)
    lbl = label.astype("int32").reshape(-1)
    picked = jnp.take_along_axis(logp, lbl[:, None], axis=-1)
    return -jnp.sum(picked)


# -------------------------------------------------------------- dropout --
@register(name="Dropout", stateful_rng=True)
def dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False,
            rng_key=None, is_train=False):
    """src/operator/nn/dropout.cc — inverted dropout; counter-based
    (threefry) RNG instead of per-resource Philox states (divergence noted
    in SURVEY §7 hard parts (f))."""
    if (not is_train and mode != "always") or p <= 0.0 or rng_key is None:
        return data
    shape = list(data.shape)
    for ax in axes:
        shape[ax] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng_key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


# ------------------------------------------------------------------ rnn --
def _lstm_cell(x, h, c, wx, wh, bx, bh):
    gates = x @ wx.T + h @ wh.T + bx + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = lax.logistic(i); f = lax.logistic(f)
    g = jnp.tanh(g); o = lax.logistic(o)
    c2 = f * c + i * g
    return o * jnp.tanh(c2), c2


def _gru_cell(x, h, wx, wh, bx, bh):
    xr, xz, xn = jnp.split(x @ wx.T + bx, 3, axis=-1)
    hr, hz, hn = jnp.split(h @ wh.T + bh, 3, axis=-1)
    r = lax.logistic(xr + hr)
    z = lax.logistic(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1 - z) * n + z * h


def _rnn_cell(x, h, wx, wh, bx, bh, act):
    return act(x @ wx.T + h @ wh.T + bx + bh)


def _gates(mode):
    return {"lstm": 4, "gru": 3, "rnn_relu": 1, "rnn_tanh": 1}[mode]


def _unpack_rnn_params(params, mode, num_layers, input_size, state_size, bidirectional):
    """Unpack MXNet's flat RNN parameter vector (rnn-inl.h layout: all
    weights layer-major then all biases)."""
    ng = _gates(mode)
    d = 2 if bidirectional else 1
    ws, bs = [], []
    off = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * d
        for _dir in range(d):
            wx = lax.dynamic_slice(params, (off,), (ng * state_size * isz,)) \
                .reshape(ng * state_size, isz)
            off += ng * state_size * isz
            wh = lax.dynamic_slice(params, (off,), (ng * state_size * state_size,)) \
                .reshape(ng * state_size, state_size)
            off += ng * state_size * state_size
            ws.append((wx, wh))
    for layer in range(num_layers):
        for _dir in range(d):
            bx = lax.dynamic_slice(params, (off,), (ng * state_size,)); off += ng * state_size
            bh = lax.dynamic_slice(params, (off,), (ng * state_size,)); off += ng * state_size
            bs.append((bx, bh))
    return ws, bs


def rnn_param_size(mode, num_layers, input_size, state_size, bidirectional=False):
    ng = _gates(mode)
    d = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * d
        total += d * ng * state_size * (isz + state_size + 2)
    return total


@register(name="RNN", num_outputs="n", stateful_rng=True)
def rnn(data, parameters, state=None, state_cell=None, state_size=1,
        num_layers=1,
        mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
        projection_size=None, lstm_state_clip_min=None,
        lstm_state_clip_max=None, lstm_state_clip_nan=False,
        use_sequence_length=False, sequence_length=None, rng_key=None,
        is_train=False):
    """src/operator/rnn.cc — fused multi-layer (bi)RNN/LSTM/GRU.

    data: (seq_len, batch, input); scanned with lax.scan so XLA pipelines
    the per-step MXU matmuls (the reference reaches cuDNN's fused kernels
    on GPU; lax.scan + fusion is the TPU analogue).
    """
    seq_len, batch, input_size = data.shape
    d = 2 if bidirectional else 1
    ws, bs = _unpack_rnn_params(parameters, mode, num_layers, input_size,
                                state_size, bidirectional)

    # omitted initial states default to zeros (lets hybridized graphs
    # avoid baking a batch-size constant for begin_state)
    if state is None:
        state = jnp.zeros((num_layers * d, batch, state_size), data.dtype)
    h0 = state  # (num_layers*d, batch, state_size)
    c0 = state_cell if mode == "lstm" else None
    if mode == "lstm" and c0 is None:
        c0 = jnp.zeros_like(h0)
    x = data
    h_last, c_last = [], []
    key = rng_key
    for layer in range(num_layers):
        outs = []
        for dr in range(d):
            li = layer * d + dr
            wx, wh = ws[li]
            bx, bh = bs[li]
            xs = jnp.flip(x, axis=0) if dr == 1 else x
            h_init = h0[li]
            if mode == "lstm":
                c_init = c0[li]

                def step(carry, xt, wx=wx, wh=wh, bx=bx, bh=bh):
                    h, c = carry
                    h2, c2 = _lstm_cell(xt, h, c, wx, wh, bx, bh)
                    return (h2, c2), h2
                (hT, cT), ys = lax.scan(step, (h_init, c_init), xs)
                c_last.append(cT)
            elif mode == "gru":
                def step(h, xt, wx=wx, wh=wh, bx=bx, bh=bh):
                    h2 = _gru_cell(xt, h, wx, wh, bx, bh)
                    return h2, h2
                hT, ys = lax.scan(step, h_init, xs)
            else:
                act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

                def step(h, xt, wx=wx, wh=wh, bx=bx, bh=bh, act=act):
                    h2 = _rnn_cell(xt, h, wx, wh, bx, bh, act)
                    return h2, h2
                hT, ys = lax.scan(step, h_init, xs)
            h_last.append(hT)
            if dr == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
        x = jnp.concatenate(outs, axis=-1) if d == 2 else outs[0]
        if p > 0.0 and is_train and layer < num_layers - 1 and key is not None:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1.0 - p, x.shape).astype(x.dtype)
            x = x * mask / (1.0 - p)
    hN = jnp.stack(h_last, axis=0)
    if mode == "lstm":
        cN = jnp.stack(c_last, axis=0)
        return x, hN, cN
    return x, hN


# ------------------------------------------------------------- ctc loss --
@register(name="CTCLoss", aliases=("ctc_loss",))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """src/operator/nn/ctc_loss.cc — forward algorithm in log space via
    lax.scan (reference uses 3rdparty/ctc_include warp-ctc)."""
    # data: (seq, batch, alphabet); label: (batch, label_len)
    seq_len, batch, alphabet = data.shape
    logp = jax.nn.log_softmax(data.astype("float32"), axis=-1)
    blank = 0 if blank_label == "first" else alphabet - 1
    lab = label.astype("int32")
    if blank_label == "first":
        lab = lab - 0  # labels already 1-based w/ blank=0 in MXNet convention? keep as-is
    L = lab.shape[1]
    # extended label: blank l1 blank l2 ... blank
    ext_len = 2 * L + 1
    ext = jnp.full((batch, ext_len), blank, dtype="int32")
    ext = ext.at[:, 1::2].set(lab)
    lab_lens = (label_lengths.astype("int32") if use_label_lengths and label_lengths is not None
                else jnp.sum((lab != blank) & (lab >= 0), axis=1).astype("int32"))
    dat_lens = (data_lengths.astype("int32") if use_data_lengths and data_lengths is not None
                else jnp.full((batch,), seq_len, dtype="int32"))
    ninf = jnp.asarray(-1e30, "float32")

    emit = jnp.take_along_axis(
        jnp.transpose(logp, (1, 0, 2)), ext[:, None, :], axis=2)  # (batch, seq, ext)
    emit = jnp.transpose(emit, (1, 0, 2))  # (seq, batch, ext)

    same = jnp.concatenate(
        [jnp.zeros((batch, 2), bool),
         ext[:, 2:] == ext[:, :-2]], axis=1)  # can't skip if same label

    alpha0 = jnp.full((batch, ext_len), ninf)
    alpha0 = alpha0.at[:, 0].set(emit[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(lab_lens > 0, emit[0, :, 1], ninf))

    def logsumexp3(a, b, c):
        m = jnp.maximum(jnp.maximum(a, b), c)
        m_safe = jnp.where(m == ninf, 0.0, m)
        return jnp.where(
            m == ninf, ninf,
            m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe) + jnp.exp(c - m_safe)))

    def step(alpha, t_emit_t):
        t, emit_t = t_emit_t
        shift1 = jnp.concatenate([jnp.full((batch, 1), ninf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((batch, 2), ninf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(same, ninf, shift2)
        new = logsumexp3(alpha, shift1, shift2) + emit_t
        new = jnp.where(t < dat_lens[:, None], new, alpha)
        return new, None

    ts = jnp.arange(1, seq_len)
    alphaT, _ = lax.scan(step, alpha0, (ts, emit[1:]))
    end1 = 2 * lab_lens
    end2 = 2 * lab_lens - 1
    aT1 = jnp.take_along_axis(alphaT, end1[:, None], axis=1)[:, 0]
    aT2 = jnp.take_along_axis(alphaT, jnp.maximum(end2, 0)[:, None], axis=1)[:, 0]
    m = jnp.maximum(aT1, aT2)
    m_safe = jnp.where(m == ninf, 0.0, m)
    ll = m_safe + jnp.log(jnp.exp(aT1 - m_safe) + jnp.exp(aT2 - m_safe))
    return (-ll).astype(data.dtype)


# ---------------------------------------------------- spatial transformer --
@register(name="SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=False):
    """src/operator/spatial_transformer.cc = GridGenerator + BilinearSampler."""
    from .matrix import grid_generator, bilinear_sampler
    grid = grid_generator(loc, transform_type="affine", target_shape=target_shape)
    return bilinear_sampler(data, grid)


@register(name="ROIPooling")
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """src/operator/roi_pooling.cc — max pool over ROI grid cells."""
    n, c, h, w = data.shape
    ph, pw = pooled_size

    def one_roi(roi):
        bidx = roi[0].astype("int32")
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bh, bw = rh / ph, rw / pw
        img = data[bidx]
        ys = jnp.arange(h).reshape(1, 1, h, 1)
        xs = jnp.arange(w).reshape(1, 1, 1, w)
        py = jnp.arange(ph).reshape(ph, 1, 1, 1)
        px = jnp.arange(pw).reshape(1, pw, 1, 1)
        y_lo = jnp.floor(y1 + py * bh); y_hi = jnp.ceil(y1 + (py + 1) * bh)
        x_lo = jnp.floor(x1 + px * bw); x_hi = jnp.ceil(x1 + (px + 1) * bw)
        mask = ((ys >= y_lo) & (ys < y_hi) & (xs >= x_lo) & (xs < x_hi))
        masked = jnp.where(mask[None], img[:, None, None], -jnp.inf)
        pooled = jnp.max(masked, axis=(3, 4))
        pooled = jnp.where(jnp.isfinite(pooled), pooled, 0.0)
        return pooled  # (c, ph, pw)

    return jax.vmap(one_roi)(rois)


# ------------------------------------------------- regression outputs ---
# src/operator/regression_output.cc — identity-ish forward, fixed bwd
# (pred - label) * grad_scale. Implemented with custom_vjp like
# SoftmaxOutput so Module loss heads train identically to the reference.

def _make_regression_output(fwd, bwd_from):
    @_functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def _core(data, label, grad_scale):
        return fwd(data)

    def _fvjp(data, label, grad_scale):
        return fwd(data), (data, label)

    def _bvjp(grad_scale, res, g):
        data, label = res
        # reference scales by grad_scale / num_output (outputs per sample,
        # regression_output-inl.h:201-207)
        num_output = data.size // data.shape[0] if data.ndim else 1
        grad = bwd_from(data, label) * (grad_scale / num_output)
        return grad, jnp.zeros_like(label)

    _core.defvjp(_fvjp, _bvjp)
    return _core


_linreg_core = _make_regression_output(
    lambda d: d,
    lambda d, l: d - l.reshape(d.shape))
_maereg_core = _make_regression_output(
    lambda d: d,
    lambda d, l: jnp.sign(d - l.reshape(d.shape)))
_logreg_core = _make_regression_output(
    jax.nn.sigmoid,
    lambda d, l: jax.nn.sigmoid(d) - l.reshape(d.shape))


# SVM head (src/operator/svm_output.cc): identity forward; backward is
# the multiclass hinge gradient (L2-SVM by default, L1 with use_linear).
@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_core(data, label, margin, reg_coef, use_linear):
    return data


def _svm_fvjp(data, label, margin, reg_coef, use_linear):
    return data, (data, label)


def _svm_bvjp(margin, reg_coef, use_linear, res, g):
    data, label = res
    lab = label.reshape(-1).astype(jnp.int32)
    x_y = jnp.take_along_axis(data, lab[:, None], axis=1)
    z = margin - x_y + data                      # (N, C); z at y == margin
    onehot = jax.nn.one_hot(lab, data.shape[1], dtype=data.dtype)
    if use_linear:
        viol = ((z > 0) & (onehot == 0)).astype(data.dtype)
    else:
        viol = jnp.where(onehot == 0, 2.0 * jnp.maximum(z, 0.0), 0.0)
    grad = reg_coef * (viol - onehot * viol.sum(axis=1, keepdims=True))
    return grad * jnp.ones_like(g), jnp.zeros_like(label)


_svm_core.defvjp(_svm_fvjp, _svm_bvjp)


@register(name="SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """src/operator/svm_output.cc — SVM loss head."""
    return _svm_core(data, label, float(margin),
                     float(regularization_coefficient), bool(use_linear))


# KL sparsity regularizer (src/operator/identity_attach_KL_sparse_reg.cc):
# identity forward; backward adds the KL(ρ||ρ̂) gradient pushing each
# unit's batch-mean activation toward sparseness_target. The reference
# keeps ρ̂ as a momentum-smoothed aux state; here ρ̂ is the batch mean
# (momentum accepted for signature parity).
@_functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _kl_sparse_core(data, sparseness_target, penalty):
    return data


def _kl_fvjp(data, sparseness_target, penalty):
    return data, data


def _kl_bvjp(sparseness_target, penalty, data, g):
    rho_hat = jnp.clip(jnp.mean(data, axis=0, keepdims=True), 1e-6,
                       1.0 - 1e-6)
    t = sparseness_target
    kl_grad = penalty * (-t / rho_hat + (1.0 - t) / (1.0 - rho_hat))
    return (g + kl_grad * jnp.ones_like(data) / data.shape[0],)


_kl_sparse_core.defvjp(_kl_fvjp, _kl_bvjp)


@register(name="IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    """src/operator/identity_attach_KL_sparse_reg.cc."""
    return _kl_sparse_core(data, float(sparseness_target), float(penalty))


@register(name="LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0):
    """src/operator/regression_output.cc:xx — identity fwd, (pred-label) bwd."""
    return _linreg_core(data, label, grad_scale)


@register(name="MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0):
    """src/operator/regression_output.cc — identity fwd, sign(pred-label) bwd."""
    return _maereg_core(data, label, grad_scale)


@register(name="LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0):
    """src/operator/regression_output.cc — sigmoid fwd, (sigmoid-label) bwd."""
    return _logreg_core(data, label, grad_scale)
