"""Contrib operators.

Reference: src/operator/contrib/ — the subset with TPU-sensible semantics:
transformer helpers (transformer.cc interleaved-matmul attention), ROIAlign,
bounding-box ops, fft/ifft, boolean_mask (dense variant), index ops,
adaptive pooling, bilinear resize, quadratic (tutorial op),
gradient_multiplier, hawkes_ll, allclose/all_finite.
"""

import jax
import jax.numpy as jnp
from jax import lax

from . import register


@register(name="_contrib_quadratic", aliases=("quadratic",))
def quadratic(data, a=0.0, b=0.0, c=0.0):
    """src/operator/contrib/quadratic_op.cc (the tutorial op)."""
    return a * data * data + b * data + c


@register(name="_contrib_gradientmultiplier")
def gradient_multiplier(data, scalar=1.0):
    """src/operator/contrib/gradient_multiplier_op.cc — identity fwd,
    scaled bwd."""
    return data * scalar - lax.stop_gradient(data * (scalar - 1.0))


@register(name="_contrib_fft")
def fft(data, compute_size=128):
    """src/operator/contrib/fft.cc — output packs (re, im) interleaved on
    the last axis, matching the reference layout."""
    f = jnp.fft.fft(data.astype("float32"), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(data.dtype)


@register(name="_contrib_ifft")
def ifft(data, compute_size=128):
    n = data.shape[-1] // 2
    x = data.astype("float32").reshape(data.shape[:-1] + (n, 2))
    c = x[..., 0] + 1j * x[..., 1]
    return jnp.fft.ifft(c, axis=-1).real.astype(data.dtype) * n


@register(name="_contrib_index_copy")
def index_copy(old, idx, new):
    return old.at[idx.astype("int32")].set(new)


@register(name="_contrib_index_array", differentiable=False)
def index_array(data, axes=None):
    shape = data.shape
    axes = tuple(axes) if axes is not None else tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij")
    out = jnp.stack([grids[a] for a in axes], axis=-1)
    return out.astype("int64")


@register(name="all_finite", differentiable=False)
def all_finite(*arrays, init_output=True):
    """src/operator/contrib/all_finite.cc — scalar 1.0 if every element of
    every input is finite (AMP loss-scaler support)."""
    ok = jnp.asarray(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a.astype("float32"))))
    return ok.astype("float32").reshape(1)


@register(name="multi_all_finite", differentiable=False)
def multi_all_finite(*arrays, num_arrays=1, init_output=True):
    return all_finite(*arrays)


@register(name="_contrib_allclose", differentiable=False)
def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=True):
    return jnp.asarray(
        jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        dtype="float32").reshape(1)


@register(name="_contrib_arange_like", differentiable=False)
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = data.size
        return (start + step * jnp.arange(n, dtype=data.dtype)).reshape(data.shape)
    n = data.shape[axis]
    return start + step * jnp.arange(n, dtype=data.dtype)


# ----------------------------------------------------------- transformer --
@register(name="_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """src/operator/contrib/transformer.cc — input (seq, batch, 3*embed)
    with q/k/v head-interleaved; returns (batch*heads, seq, seq) scores."""
    s, b, e3 = queries_keys_values.shape
    e = e3 // 3
    hd = e // heads
    x = queries_keys_values.reshape(s, b, heads, 3, hd)
    q = x[:, :, :, 0]  # (s, b, h, hd)
    k = x[:, :, :, 1]
    q = jnp.transpose(q, (1, 2, 0, 3)).reshape(b * heads, s, hd)
    k = jnp.transpose(k, (1, 2, 0, 3)).reshape(b * heads, s, hd)
    return jnp.matmul(q, jnp.swapaxes(k, -1, -2)) / jnp.sqrt(
        jnp.asarray(hd, q.dtype))


@register(name="_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    s, b, e3 = queries_keys_values.shape
    e = e3 // 3
    hd = e // heads
    x = queries_keys_values.reshape(s, b, heads, 3, hd)
    v = jnp.transpose(x[:, :, :, 2], (1, 2, 0, 3)).reshape(b * heads, s, hd)
    out = jnp.matmul(attention, v)  # (b*h, s, hd)
    out = out.reshape(b, heads, s, hd)
    return jnp.transpose(out, (2, 0, 1, 3)).reshape(s, b, e)


@register(name="_contrib_div_sqrt_dim")
def div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


# ------------------------------------------------------------- roi align --
@register(name="_contrib_ROIAlign")
def roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """src/operator/contrib/roi_align.cc — bilinear-sampled average pool.

    Divergence (documented): sample_ratio<=0 means an ADAPTIVE
    ceil(roi/pool) grid in the reference — data-dependent shape, so
    under jit we fix it to 2x2 (same estimator). Border rule matches
    the reference: samples beyond one pixel outside the map contribute
    zero; nearer ones clamp to the edge before bilinear weighting."""
    n, c, h, w = data.shape
    ph, pw = pooled_size
    sr = 2 if sample_ratio <= 0 else sample_ratio
    off = 0.5 if aligned else 0.0

    def one(roi):
        bidx = roi[0].astype("int32")
        x1 = roi[1] * spatial_scale - off
        y1 = roi[2] * spatial_scale - off
        x2 = roi[3] * spatial_scale - off
        y2 = roi[4] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bw, bh = rw / pw, rh / ph
        img = data[bidx]  # (c, h, w)
        py = jnp.arange(ph)
        px = jnp.arange(pw)
        sy = jnp.arange(sr)
        sx = jnp.arange(sr)
        yy = y1 + (py[:, None] + (sy[None, :] + 0.5) / sr) * bh  # (ph, sr)
        xx = x1 + (px[:, None] + (sx[None, :] + 0.5) / sr) * bw  # (pw, sr)
        yg = yy.reshape(-1)  # ph*sr
        xg = xx.reshape(-1)  # pw*sr

        # reference border rule (roi_align.cc bilinear_interpolate):
        # a sample more than ONE pixel outside the map contributes 0;
        # within that margin it clamps to the edge
        vy = (yg >= -1.0) & (yg <= h)
        vx = (xg >= -1.0) & (xg <= w)
        y0 = jnp.clip(jnp.floor(yg), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xg), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype("int32")
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype("int32")
        y0i = y0.astype("int32"); x0i = x0.astype("int32")
        wy1 = jnp.clip(yg, 0, h - 1) - y0; wy0 = 1 - wy1
        wx1 = jnp.clip(xg, 0, w - 1) - x0; wx0 = 1 - wx1
        g = (img[:, y0i][:, :, x0i] * (wy0[:, None] * wx0[None, :])
             + img[:, y0i][:, :, x1i] * (wy0[:, None] * wx1[None, :])
             + img[:, y1i][:, :, x0i] * (wy1[:, None] * wx0[None, :])
             + img[:, y1i][:, :, x1i] * (wy1[:, None] * wx1[None, :]))
        g = g * (vy[:, None] & vx[None, :])
        g = g.reshape(c, ph, sr, pw, sr)
        pooled = jnp.mean(g, axis=(2, 4))                # (c, ph, pw)
        if not position_sensitive:
            return pooled
        # R-FCN variant (roi_align.cc: c_in = ctop*ph*pw + py*pw + px):
        # bin (py, px) of output channel ctop reads its own channel
        # group — a per-bin channel gather after the uniform pooling
        c_out = c // (ph * pw)
        r = pooled.reshape(c_out, ph, pw, ph, pw)
        iy, ix = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw),
                              indexing="ij")
        return r[:, iy, ix, iy, ix]

    if position_sensitive and c % (ph * pw):
        raise ValueError(
            "position_sensitive ROIAlign needs channels (%d) divisible "
            "by pooled_h*pooled_w (%d)" % (c, ph * pw))
    return jax.vmap(one)(rois)


# ---------------------------------------------------------- bounding box --
@register(name="_contrib_box_iou")
def box_iou(lhs, rhs, format="corner"):
    """src/operator/contrib/bounding_box.cc box_iou."""
    def to_corner(b):
        if format == "center":
            x, y, w_, h_ = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
            return jnp.stack([x - w_ / 2, y - h_ / 2, x + w_ / 2, y + h_ / 2], -1)
        return b
    a = to_corner(lhs)[..., None, :]
    b = to_corner(rhs)[None, ...]
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register(name="_contrib_box_nms", aliases=("_contrib_box_non_maximum_suppression",),
          differentiable=False)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Greedy NMS with a fixed iteration bound (static shapes for XLA)."""
    boxes = data[..., coord_start:coord_start + 4]
    scores = data[..., score_index]
    n = data.shape[-2]

    def nms_one(boxes_i, scores_i, data_i):
        order = jnp.argsort(-scores_i)
        boxes_s = boxes_i[order]
        scores_s = scores_i[order]
        valid = scores_s > valid_thresh

        tl = jnp.maximum(boxes_s[:, None, :2], boxes_s[None, :, :2])
        br = jnp.minimum(boxes_s[:, None, 2:], boxes_s[None, :, 2:])
        wh = jnp.maximum(br - tl, 0)
        inter = wh[..., 0] * wh[..., 1]
        area = (boxes_s[:, 2] - boxes_s[:, 0]) * (boxes_s[:, 3] - boxes_s[:, 1])
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-12)

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & keep[i] & (jnp.arange(n) > i)
            return jnp.where(sup, False, keep)
        keep = lax.fori_loop(0, n, body, valid)
        out = data_i[order]
        return jnp.where(keep[:, None], out, -1.0)

    flat = data.reshape(-1, n, data.shape[-1])
    out = jax.vmap(nms_one)(flat[..., coord_start:coord_start + 4],
                            flat[..., score_index], flat)
    return out.reshape(data.shape)


# ------------------------------------------------------ adaptive pooling --
@register(name="_contrib_AdaptiveAvgPooling2D")
def adaptive_avg_pooling(data, output_size=()):
    """src/operator/contrib/adaptive_avg_pooling.cc."""
    if not output_size:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = (output_size[0], output_size[0]) if len(output_size) == 1 else output_size
    n, c, h, w = data.shape
    # integral-image formulation keeps everything static-shape
    ys = (jnp.arange(oh + 1) * h) // oh
    xs = (jnp.arange(ow + 1) * w) // ow
    integ = jnp.cumsum(jnp.cumsum(data, axis=2), axis=3)
    integ = jnp.pad(integ, ((0, 0), (0, 0), (1, 0), (1, 0)))
    s = (integ[:, :, ys[1:], :][:, :, :, xs[1:]]
         - integ[:, :, ys[:-1], :][:, :, :, xs[1:]]
         - integ[:, :, ys[1:], :][:, :, :, xs[:-1]]
         + integ[:, :, ys[:-1], :][:, :, :, xs[:-1]])
    counts = ((ys[1:] - ys[:-1])[:, None] * (xs[1:] - xs[:-1])[None, :]).astype(data.dtype)
    return s / counts


@register(name="_contrib_BilinearResize2D")
def bilinear_resize(data, height=1, width=1, scale_height=None, scale_width=None,
                    mode="size", align_corners=True):
    """src/operator/contrib/bilinear_resize.cc."""
    n, c, h, w = data.shape
    if scale_height is not None:
        height = int(h * scale_height)
        width = int(w * scale_width)
    return jax.image.resize(data, (n, c, int(height), int(width)),
                            method="linear")


@register(name="_contrib_hawkesll", num_outputs=2)
def hawkes_ll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """src/operator/contrib/hawkes_ll.cc — log-likelihood of a marked
    self-exciting (Hawkes) process on [0, max_time].

    Matches the reference kernel exactly (hawkesll_forward /
    hawkesll_forward_compensator in hawkes_ll-inl.h): per event j with
    mark m at cumulative time t, intensity
    lam = mu[m] + alpha[m] * beta[m] * state_m(t); the compensator
    integral splits into the background part sum_k mu_k * max_time
    (per-mark inter-event gaps tile [0, T]) and the excitation part,
    which telescopes: state only decays between events and jumps +1 at
    its mark's events, so
    integral(excitation_k) = alpha_k * (state0_k + N_k - state_k(T))
    with N_k the mark's event count and state_k(T) the returned state,
    decayed through to max_time (the reference decays it the same way
    so windows chain across minibatch calls)."""
    # lda: (N,K) background; alpha,beta: (K,); lags,marks: (N,T)
    N, T = lags.shape

    def one(lda_i, state_i, lags_i, marks_i, vl_i, mt_i):
        def step(carry, t):
            ll, rem, elapsed, counts = carry
            m = marks_i[t].astype("int32")
            valid = (t < vl_i).astype(lda_i.dtype)
            dt = lags_i[t] * valid        # padded steps advance nothing
            rem = rem * jnp.exp(-beta * dt)
            lam = lda_i[m] + alpha[m] * beta[m] * rem[m]
            ll = ll + valid * jnp.log(jnp.maximum(lam, 1e-20))
            rem = rem.at[m].add(valid)
            counts = counts.at[m].add(valid)
            return (ll, rem, elapsed + dt, counts), None

        zero = jnp.asarray(0.0, lda.dtype)
        (ll, rem, elapsed, counts), _ = lax.scan(
            step,
            (zero, state_i, zero, jnp.zeros_like(state_i)),
            jnp.arange(T))
        # decay the state through the tail [t_last, max_time]
        rem_T = rem * jnp.exp(-beta * (mt_i - elapsed))
        compens = (jnp.sum(lda_i) * mt_i
                   + jnp.sum(alpha * (state_i + counts - rem_T)))
        return ll - compens, rem_T

    ll, states = jax.vmap(one)(lda, state, lags, marks, valid_length,
                               jnp.broadcast_to(max_time, (N,)))
    return ll, states


@register(name="_contrib_count_sketch")
def count_sketch(data, h, s, out_dim=16, processing_batch_size=32):
    """src/operator/contrib/count_sketch.cc."""
    idx = h.astype("int32").reshape(-1)
    sign = s.reshape(-1)
    out = jnp.zeros(data.shape[:-1] + (out_dim,), dtype=data.dtype)
    return out.at[..., idx].add(data * sign)


@register(name="_contrib_RROIAlign", differentiable=False)
def rroi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
               sampling_ratio=-1):
    """src/operator/contrib/rroi_align.cc — rotated ROI align. rois are
    [batch_idx, xc, yc, w, h, theta_degrees]; each pooled bin averages a
    grid of bilinear samples taken on the rotated box. The reference's
    adaptive grid (ceil(roi/pool)) is data-dependent; under jit we fix
    the grid to 2x2 when sampling_ratio<=0 (documented divergence)."""
    n, c, h, w = data.shape
    ph, pw = pooled_size
    sr = 2 if sampling_ratio <= 0 else sampling_ratio

    def one(roi):
        img = data[roi[0].astype("int32")]
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        rw = jnp.maximum(roi[3] * spatial_scale, 1.0)
        rh = jnp.maximum(roi[4] * spatial_scale, 1.0)
        theta = roi[5] * jnp.pi / 180.0
        ct, st = jnp.cos(theta), jnp.sin(theta)
        bh, bw = rh / ph, rw / pw
        py, sy = jnp.arange(ph), jnp.arange(sr)
        px, sx = jnp.arange(pw), jnp.arange(sr)
        yy = -rh / 2 + py[:, None] * bh + (sy[None, :] + 0.5) * bh / sr
        xx = -rw / 2 + px[:, None] * bw + (sx[None, :] + 0.5) * bw / sr
        yg = yy.reshape(-1)[:, None]            # (ph*sr, 1)
        xg = xx.reshape(-1)[None, :]            # (1, pw*sr)
        x = xg * ct + yg * st + cx              # (ph*sr, pw*sr)
        y = yg * ct - xg * st + cy
        inside = (y >= -1.0) & (y <= h) & (x >= -1.0) & (x <= w)
        y = jnp.clip(y, 0, h - 1)
        x = jnp.clip(x, 0, w - 1)
        y0 = jnp.floor(y); x0 = jnp.floor(x)
        y0i = y0.astype("int32"); x0i = x0.astype("int32")
        y1i = jnp.minimum(y0i + 1, h - 1); x1i = jnp.minimum(x0i + 1, w - 1)
        wy1 = y - y0; wy0 = 1 - wy1
        wx1 = x - x0; wx0 = 1 - wx1
        flat = img.reshape(c, -1)
        def gather(yi, xi):
            return flat[:, (yi * w + xi).reshape(-1)].reshape((c,) + y.shape)
        g = (gather(y0i, x0i) * (wy0 * wx0) + gather(y0i, x1i) * (wy0 * wx1)
             + gather(y1i, x0i) * (wy1 * wx0) + gather(y1i, x1i) * (wy1 * wx1))
        g = jnp.where(inside, g, 0.0)
        g = g.reshape(c, ph, sr, pw, sr)
        return jnp.mean(g, axis=(2, 4))

    return jax.vmap(one)(rois)


@register(name="_contrib_bipartite_matching", num_outputs=2,
          differentiable=False)
def bipartite_matching(data, is_ascend=False, threshold=1e-12, topk=-1):
    """bounding_box.cc `_contrib_bipartite_matching` — greedy score-ordered
    matching. data: (..., row, col); returns (row_match, col_match) holding
    the matched counterpart index or -1. The reference sorts all scores and
    walks them greedily; iteratively extracting the best unmatched pair is
    the same argument order expressed as a lax loop."""
    shape = data.shape
    row, col = shape[-2], shape[-1]
    flat = data.reshape((-1, row, col)).astype(jnp.float32)
    steps = min(row, col) if topk < 0 else min(topk, min(row, col))
    big = jnp.float32(3.4e38)
    sgn = 1.0 if is_ascend else -1.0

    def one(mat):
        def body(_, state):
            rm, cm = state
            masked = jnp.where((rm[:, None] < 0) & (cm[None, :] < 0),
                               sgn * mat, big)
            idx = jnp.argmin(masked.reshape(-1))
            r, cidx = idx // col, idx % col
            s = mat[r, cidx]
            ok = (s <= threshold) if is_ascend else (s >= threshold)
            ok &= masked[r, cidx] < big
            rm = jnp.where(ok, rm.at[r].set(cidx), rm)
            cm = jnp.where(ok, cm.at[cidx].set(r), cm)
            return rm, cm
        rm, cm = jax.lax.fori_loop(0, steps, body,
                                   (jnp.full((row,), -1.0, jnp.float32),
                                    jnp.full((col,), -1.0, jnp.float32)))
        return rm, cm

    rms, cms = jax.vmap(one)(flat)
    return (rms.reshape(shape[:-2] + (row,)).astype(data.dtype),
            cms.reshape(shape[:-2] + (col,)).astype(data.dtype))


@register(name="_contrib_SparseEmbedding")
def sparse_embedding(data, weight, input_dim=1, output_dim=1,
                     dtype="float32", sparse_grad=True):
    """contrib SparseEmbedding — identical forward to Embedding; the
    reference's row_sparse gradient storage is a dense gradient here
    (SURVEY §7 hard part (a): sparse-as-dense divergence)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)
