"""Elementwise unary/binary operators.

Reference: src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_op_basic.cc, elemwise_binary_broadcast_op_*.cc,
elemwise_binary_scalar_op_*.cc. On TPU these are single XLA HLO ops; XLA
fuses chains of them into the surrounding matmuls/convs, which is the
fusion the reference implemented by hand with mshadow expression templates
(3rdparty/mshadow/mshadow/tensor.h:365).

MXNet binary ops broadcast explicitly (`broadcast_add`) vs. elemwise
(`elemwise_add` requires equal shapes); both are registered, both lower to
jnp broadcasting (shape-checked for the elemwise_ variants).
"""

import jax.numpy as jnp
from jax import lax
from jax.scipy.special import erf as _erf, erfinv as _erfinv, gammaln as _gammaln

from . import register


def _check_same_shape(a, b, name):
    if a.shape != b.shape:
        raise ValueError("%s requires identical shapes, got %s vs %s"
                         % (name, a.shape, b.shape))


# ---------------------------------------------------------------- binary --
def _binary(name, fn, broadcast_alias=None):
    @register(name=name, aliases=(broadcast_alias,) if broadcast_alias else ())
    def _op(lhs, rhs, _name=name, _fn=fn):
        return _fn(lhs, rhs)
    return _op


for _n, _f in [
    ("broadcast_add", jnp.add), ("broadcast_sub", jnp.subtract),
    ("broadcast_mul", jnp.multiply), ("broadcast_div", jnp.divide),
    ("broadcast_mod", jnp.mod), ("broadcast_power", jnp.power),
    ("broadcast_maximum", jnp.maximum), ("broadcast_minimum", jnp.minimum),
    ("broadcast_hypot", jnp.hypot),
    ("broadcast_equal", lambda a, b: (a == b).astype(a.dtype)),
    ("broadcast_not_equal", lambda a, b: (a != b).astype(a.dtype)),
    ("broadcast_greater", lambda a, b: (a > b).astype(a.dtype)),
    ("broadcast_greater_equal", lambda a, b: (a >= b).astype(a.dtype)),
    ("broadcast_lesser", lambda a, b: (a < b).astype(a.dtype)),
    ("broadcast_lesser_equal", lambda a, b: (a <= b).astype(a.dtype)),
    ("broadcast_logical_and", lambda a, b: (jnp.logical_and(a != 0, b != 0)).astype(a.dtype)),
    ("broadcast_logical_or", lambda a, b: (jnp.logical_or(a != 0, b != 0)).astype(a.dtype)),
    ("broadcast_logical_xor", lambda a, b: (jnp.logical_xor(a != 0, b != 0)).astype(a.dtype)),
]:
    _binary(_n, _f)

# elemwise_* versions (strict same-shape; src/operator/tensor/elemwise_binary_op_basic.cc)
for _n, _f in [
    ("elemwise_add", jnp.add), ("elemwise_sub", jnp.subtract),
    ("elemwise_mul", jnp.multiply), ("elemwise_div", jnp.divide),
]:
    def _mk(n=_n, f=_f):
        @register(name=n, aliases=("_" + n.split("_")[1],))
        def _op(lhs, rhs):
            _check_same_shape(lhs, rhs, n)
            return f(lhs, rhs)
    _mk()


# ---------------------------------------------------------------- scalar --
# Scalar operand is a static attr (src/operator/tensor/elemwise_binary_scalar_op_basic.cc)
for _n, _f in [
    ("_plus_scalar", lambda x, scalar: x + scalar),
    ("_minus_scalar", lambda x, scalar: x - scalar),
    ("_rminus_scalar", lambda x, scalar: scalar - x),
    ("_mul_scalar", lambda x, scalar: x * scalar),
    ("_div_scalar", lambda x, scalar: x / scalar),
    ("_rdiv_scalar", lambda x, scalar: scalar / x),
    ("_mod_scalar", lambda x, scalar: jnp.mod(x, scalar)),
    ("_rmod_scalar", lambda x, scalar: jnp.mod(scalar, x)),
    ("_power_scalar", lambda x, scalar: jnp.power(x, scalar)),
    ("_rpower_scalar", lambda x, scalar: jnp.power(scalar, x)),
    ("_maximum_scalar", lambda x, scalar: jnp.maximum(x, scalar)),
    ("_minimum_scalar", lambda x, scalar: jnp.minimum(x, scalar)),
    ("_equal_scalar", lambda x, scalar: (x == scalar).astype(x.dtype)),
    ("_not_equal_scalar", lambda x, scalar: (x != scalar).astype(x.dtype)),
    ("_greater_scalar", lambda x, scalar: (x > scalar).astype(x.dtype)),
    ("_greater_equal_scalar", lambda x, scalar: (x >= scalar).astype(x.dtype)),
    ("_lesser_scalar", lambda x, scalar: (x < scalar).astype(x.dtype)),
    ("_lesser_equal_scalar", lambda x, scalar: (x <= scalar).astype(x.dtype)),
    ("_hypot_scalar", lambda x, scalar: jnp.hypot(x, jnp.asarray(scalar, x.dtype))),
]:
    def _mks(n=_n, f=_f):
        @register(name=n)
        def _op(data, scalar=0.0):
            return f(data, scalar)
    _mks()


# ----------------------------------------------------------------- unary --
def _softrelu(x):
    # log(1+exp(x)), numerically stable (src/operator/mshadow_op.h softrelu)
    return jnp.logaddexp(x, 0.0)


_UNARY = [
    ("negative", jnp.negative), ("reciprocal", jnp.reciprocal),
    ("abs", jnp.abs), ("sign", jnp.sign),
    ("round", jnp.round), ("rint", jnp.rint), ("ceil", jnp.ceil),
    ("floor", jnp.floor), ("trunc", jnp.trunc), ("fix", jnp.trunc),
    ("square", jnp.square), ("sqrt", jnp.sqrt),
    ("rsqrt", lambda x: lax.rsqrt(x)), ("cbrt", jnp.cbrt),
    ("rcbrt", lambda x: 1.0 / jnp.cbrt(x)),
    ("exp", jnp.exp), ("log", jnp.log), ("log10", jnp.log10),
    ("log2", jnp.log2), ("log1p", jnp.log1p), ("expm1", jnp.expm1),
    ("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
    ("arcsin", jnp.arcsin), ("arccos", jnp.arccos), ("arctan", jnp.arctan),
    ("sinh", jnp.sinh), ("cosh", jnp.cosh), ("tanh", jnp.tanh),
    ("arcsinh", jnp.arcsinh), ("arccosh", jnp.arccosh), ("arctanh", jnp.arctanh),
    ("degrees", jnp.degrees), ("radians", jnp.radians),
    ("erf", _erf), ("erfinv", _erfinv), ("gamma", lambda x: jnp.exp(_gammaln(x))),
    ("gammaln", _gammaln),
    ("sigmoid", lambda x: jax_sigmoid(x)),
    ("hard_sigmoid", lambda x, alpha=0.2, beta=0.5: jnp.clip(alpha * x + beta, 0.0, 1.0)),
    ("relu", lambda x: jnp.maximum(x, 0)),
    ("softsign", lambda x: x / (1 + jnp.abs(x))),
    ("logical_not", lambda x: (x == 0).astype(x.dtype)),
    ("isfinite", lambda x: jnp.isfinite(x).astype(jnp.float32)),
    ("isnan", lambda x: jnp.isnan(x).astype(jnp.float32)),
    ("isinf", lambda x: jnp.isinf(x).astype(jnp.float32)),
]


def jax_sigmoid(x):
    return lax.logistic(x)


for _n, _f in _UNARY:
    def _mku(n=_n, f=_f):
        @register(name=n)
        def _op(data, **kw):
            return f(data, **kw) if kw else f(data)
    _mku()


@register(name="smooth_l1")
def smooth_l1(data, scalar=1.0):
    """src/operator/tensor/elemwise_binary_scalar_op_extended.cc —
    f(x) = 0.5 (sx)^2 for |x| < 1/s^2, |x| - 0.5/s^2 otherwise."""
    sigma2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / sigma2,
                     0.5 * sigma2 * data * data,
                     jnp.abs(data) - 0.5 / sigma2)


@register(name="softrelu")
def softrelu(data):
    return _softrelu(data)


@register(name="clip")
def clip(data, a_min=0.0, a_max=1.0):
    """src/operator/tensor/matrix_op.cc clip."""
    return jnp.clip(data, a_min, a_max)


@register(name="_copy", aliases=("identity", "stop_gradient_identity"))
def _copy(data):
    return data


@register(name="BlockGrad", aliases=("stop_gradient",), differentiable=False)
def block_grad(data):
    """src/operator/tensor/elemwise_unary_op_basic.cc BlockGrad."""
    return lax.stop_gradient(data)


@register(name="make_loss")
def make_loss(data, grad_scale=1.0):
    """src/operator/make_loss.cc — identity fwd, grad_scale*ones bwd.

    Under jax.vjp the natural formulation is fwd = data, and the head
    gradient seeding handles scale; we emulate by scaling in fwd-transpose:
    make_loss(x) == x * grad_scale - stop_grad(x * (grad_scale-1))."""
    if grad_scale == 1.0:
        return data
    return data * grad_scale - lax.stop_gradient(data * (grad_scale - 1.0))


@register(name="Cast", aliases=("cast",))
def cast(data, dtype="float32"):
    return data.astype(jnp.dtype(dtype))


@register(name="amp_cast")
def amp_cast(data, dtype="float16"):
    """src/operator/tensor/amp_cast.cc — AMP narrowing cast; on TPU the
    low-precision type is bfloat16 and float16 requests map to it."""
    dt = jnp.dtype("bfloat16") if str(dtype) == "float16" else jnp.dtype(dtype)
    return data.astype(dt)


@register(name="amp_multicast", num_outputs="n")
def amp_multicast(*data, num_outputs=1):
    widest = jnp.result_type(*[d.dtype for d in data])
    return tuple(d.astype(widest) for d in data)


@register(name="add_n", aliases=("ElementWiseSum",))
def add_n(*args):
    """src/operator/tensor/elemwise_sum.cc — sum of N arrays in one pass."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out
