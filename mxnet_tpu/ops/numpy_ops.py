"""NumPy-semantics internal operators (`_np_*` / `_npi_*`).

Reference: src/operator/numpy/ — the registered kernels behind `mx.np`.
In this framework `mx.np` delegates straight to jnp (numpy/__init__.py),
so these registrations exist for graph-level parity: symbols and Symbol
JSON produced by reference numpy frontends resolve to real ops here.
Semantics are NumPy's (axis=None reduces everything, dtype kwargs,
true-division), unlike the classic ops' MXNet conventions.
"""

import jax
import jax.numpy as jnp

from . import register


@register(name="_np_sum", aliases=("_npi_sum",))
def np_sum(a, axis=None, dtype=None, keepdims=False, initial=None):
    out = jnp.sum(a, axis=axis, keepdims=keepdims,
                  dtype=jnp.dtype(dtype) if dtype else None)
    return out + initial if initial is not None else out


@register(name="_np_prod")
def np_prod(a, axis=None, dtype=None, keepdims=False):
    return jnp.prod(a, axis=axis, keepdims=keepdims,
                    dtype=jnp.dtype(dtype) if dtype else None)


@register(name="_np_cumsum", aliases=("_npi_cumsum",))
def np_cumsum(a, axis=None, dtype=None):
    return jnp.cumsum(a, axis=axis,
                      dtype=jnp.dtype(dtype) if dtype else None)


@register(name="_np_dot")
def np_dot(a, b):
    return jnp.dot(a, b)


@register(name="_npi_tensordot")
def npi_tensordot(a, b, a_axes_summed=(), b_axes_summed=()):
    return jnp.tensordot(a, b, axes=(tuple(a_axes_summed),
                                     tuple(b_axes_summed)))


@register(name="_npi_tensordot_int_axes")
def npi_tensordot_int_axes(a, b, axes=2):
    return jnp.tensordot(a, b, axes=int(axes))


@register(name="_np_transpose")
def np_transpose(a, axes=None):
    return jnp.transpose(a, axes=tuple(axes) if axes else None)


@register(name="_np_reshape", aliases=("_npi_reshape",))
def np_reshape(a, newshape=(), order="C"):
    return jnp.reshape(a, newshape)


@register(name="_np_squeeze")
def np_squeeze(a, axis=None):
    return jnp.squeeze(a, axis=axis)


@register(name="_np_broadcast_to", aliases=("_npi_broadcast_to",))
def np_broadcast_to(array, shape=()):
    return jnp.broadcast_to(array, tuple(shape))


@register(name="_np_copy")
def np_copy(a):
    return jnp.asarray(a)


@register(name="_np_ones_like")
def np_ones_like(a):
    return jnp.ones_like(a)


@register(name="_np_zeros_like")
def np_zeros_like(a):
    return jnp.zeros_like(a)


@register(name="_npi_zeros", differentiable=False)
def npi_zeros(shape=(), dtype="float32"):
    return jnp.zeros(tuple(shape), jnp.dtype(dtype))


@register(name="_npi_ones", differentiable=False)
def npi_ones(shape=(), dtype="float32"):
    return jnp.ones(tuple(shape), jnp.dtype(dtype))


@register(name="_npi_arange", differentiable=False)
def npi_arange(start=0, stop=None, step=1, dtype="float32"):
    return jnp.arange(start, stop, step, jnp.dtype(dtype))


@register(name="_npi_argmax", differentiable=False)
def npi_argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis)
    return jnp.expand_dims(out, axis) if keepdims and axis is not None else out


@register(name="_npi_log")
def npi_log(x):
    return jnp.log(x)


@register(name="_npi_concatenate", aliases=("_npi_stack_concat_guard",))
def npi_concatenate(*data, axis=0):
    if axis is None:
        return jnp.concatenate([d.reshape(-1) for d in data], axis=0)
    return jnp.concatenate(data, axis=axis)


@register(name="_npi_stack")
def npi_stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@register(name="_npi_true_divide")
def npi_true_divide(lhs, rhs):
    return jnp.true_divide(lhs, rhs)


@register(name="_npi_true_divide_scalar")
def npi_true_divide_scalar(data, scalar=1.0):
    return jnp.true_divide(data, scalar)


@register(name="_npi_rtrue_divide_scalar")
def npi_rtrue_divide_scalar(data, scalar=1.0):
    return jnp.true_divide(scalar, data)


@register(name="_npi_uniform", differentiable=False, stateful_rng=True)
def npi_uniform(low=0.0, high=1.0, size=(), dtype="float32", rng_key=None):
    size = (size,) if isinstance(size, int) else tuple(size or ())
    return jax.random.uniform(rng_key, size, jnp.dtype(dtype),
                              minval=low, maxval=high)
