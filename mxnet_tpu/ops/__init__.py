"""Operator registry — the TPU-native analogue of the NNVM op registry.

Reference: 345 `NNVM_REGISTER_OP` registrations under src/operator/ with
attribute functions FInferShape/FInferType/FCompute/FGradient consumed by the
imperative and symbolic runtimes (include/mxnet/op_attr_types.h, dispatch in
src/imperative/imperative_utils.h:394-560).

TPU-native design: an op's "kernel" is a pure JAX function over jax.Array
inputs. That single artifact subsumes most of the reference's attribute
machinery:
  * FCompute            -> the function itself (XLA-compiled on dispatch)
  * FInferShape/Type    -> jax.eval_shape on the function (free, exact)
  * FGradient           -> jax.vjp on the function (free, exact)
  * FInplaceOption      -> XLA buffer aliasing / donation
  * dispatch modes      -> XLA backend selection; no sparse/MKLDNN forks

What the registry still owns: the op *name* surface (so `nd.*`, `sym.*` and
Symbol JSON stay MXNet-compatible), parameter parsing/validation, and
flags (non-differentiable outputs, rng statefulness, mutable inputs).

Deliberately unregistered reference names: the explicitly-registered
backward ops (`_broadcast_backward`, `_contrib_backward_*`,
`_split_v2_backward`, ...) — gradients come from jax.vjp on the forward
fn, so backward never exists as a standalone graph node here. `Custom`
registers late (operator._register_symbolic): user callbacks are staged
into compiled graphs via jax.pure_callback with the user-defined
backward as a custom_vjp, mirroring the reference's dedicated
custom-op host thread (src/operator/custom/custom.cc).
"""

import functools
import inspect

from ..base import MXNetError

_REGISTRY = {}
_ALIAS = {}


class Op:
    """A registered operator.

    `fn(*arrays, **attrs)` must be a pure JAX-traceable function: arrays are
    jax.Array (or pytrees of them for multi-output ops), attrs are static
    python values. Multi-output ops return a tuple/list.
    """

    def __init__(self, name, fn, differentiable=True, stateful_rng=False,
                 num_outputs=1, mutate_inputs=()):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.stateful_rng = stateful_rng
        self.num_outputs = num_outputs
        # names, positions, or a callable attrs -> positions (for ops
        # whose state slots depend on an attr, e.g. num_weights)
        self.mutate_inputs = mutate_inputs if callable(mutate_inputs) \
            else tuple(mutate_inputs)
        self._sig = None
        # dispatch-time caches (filled on first use; see op_signature /
        # op_dispatch_meta): re-deriving these with inspect on every
        # eager call measurably costs in the small-op hot loop
        self._has_varargs = None
        self._param_names = None

    def make_fn(self, attrs):
        """Close the op over static attrs -> pure fn(*arrays)."""
        fn = self.fn
        if not attrs:
            return fn
        return functools.partial(fn, **attrs)

    def __repr__(self):
        return "Op(%s)" % self.name


def register(name=None, aliases=(), differentiable=True, stateful_rng=False,
             num_outputs=1, mutate_inputs=()):
    """Decorator: register a pure jax function as an operator."""
    def deco(fn):
        opname = name or fn.__name__
        op = Op(opname, fn, differentiable=differentiable,
                stateful_rng=stateful_rng, num_outputs=num_outputs,
                mutate_inputs=mutate_inputs)
        _REGISTRY[opname] = op
        for a in aliases:
            _ALIAS[a] = opname
        return fn
    return deco


def get(name):
    op = _REGISTRY.get(name)
    if op is None:
        real = _ALIAS.get(name)
        if real is not None:
            op = _REGISTRY[real]
    if op is None:
        raise MXNetError("Operator %s is not registered" % name)
    return op


def exists(name):
    return name in _REGISTRY or name in _ALIAS


def list_ops():
    return sorted(_REGISTRY)


def op_signature(name):
    op = get(name)
    if op._sig is None:
        op._sig = inspect.signature(op.fn)
    return op._sig


def op_dispatch_meta(op):
    """(has_varargs, param_names) cached on the Op — the eager dispatch
    hot loop must not re-walk inspect.Parameter objects per call
    (reference concern: SURVEY §3.1 per-op dispatch latency)."""
    if op._has_varargs is None:
        if op._sig is None:
            op._sig = inspect.signature(op.fn)
        params = op._sig.parameters
        op._has_varargs = any(
            p.kind == inspect.Parameter.VAR_POSITIONAL
            for p in params.values())
        op._param_names = tuple(params)
    return op._has_varargs, op._param_names


# Import op definition modules so the registry is populated at import time
# (mirrors static NNVM_REGISTER_OP initializers linking into libmxnet.so).
from . import elemwise  # noqa: E402,F401
from . import reduce_ops  # noqa: E402,F401
from . import matrix  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from . import random_ops  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import contrib_ops  # noqa: E402,F401
from . import vision_ops  # noqa: E402,F401
from . import optimizer_ops  # noqa: E402,F401
from . import image_ops  # noqa: E402,F401
from . import control_flow_ops  # noqa: E402,F401
from . import quantization_ops  # noqa: E402,F401
from . import numpy_ops  # noqa: E402,F401
