"""Detection / optical-flow operator family.

Reference semantics: src/operator/correlation.cc (FlowNet correlation),
src/operator/contrib/multibox_prior.cc / multibox_target.cc /
multibox_detection.cc (SSD), src/operator/contrib/proposal.cc
(Faster-RCNN RPN), src/operator/contrib/deformable_convolution.cc and
deformable_psroi_pooling.cc (DCN / R-FCN).

TPU-native shapes: everything is static — displacement grids unroll at
trace time, NMS is a fixed-trip-count lax.fori_loop over a top-k set,
and ragged results are padded with -1 instead of being dynamically
sized. Bilinear sampling (deformable ops) is expressed as four gathers
with blend weights, which XLA lowers to vectorized dynamic-slices.
"""

import jax
import jax.numpy as jnp
from jax import lax

from . import register


# ------------------------------------------------------------ correlation --
@register(name="Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation volume between two feature maps.

    Output channel (j, i) holds the kernel-window-averaged, channel-summed
    product (or |difference|) of data1 and data2 displaced by
    (j*stride2, i*stride2), scaled by 1/(K*K*C) as the reference does.
    """
    b, c, h, w = data1.shape
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    ph, pw = h + 2 * pad_size, w + 2 * pad_size
    top_h = (ph - 2 * border + stride1 - 1) // stride1
    top_w = (pw - 2 * border + stride1 - 1) // stride1
    radius = max_displacement // stride2
    grid = 2 * radius + 1
    norm = float(kernel_size * kernel_size * c)

    planes = []
    for j in range(-radius, radius + 1):
        for i in range(-radius, radius + 1):
            dy, dx = j * stride2, i * stride2
            shifted = p2[:, :, max_displacement + dy:
                         ph - max_displacement + dy,
                         max_displacement + dx:pw - max_displacement + dx]
            base = p1[:, :, max_displacement:ph - max_displacement,
                      max_displacement:pw - max_displacement]
            if is_multiply:
                prod = base * shifted
            else:
                prod = jnp.abs(base - shifted)
            summed = jnp.sum(prod, axis=1, keepdims=True)
            # kernel-window sum centred on the stride1 grid
            win = lax.reduce_window(
                summed, 0.0, lax.add,
                (1, 1, kernel_size, kernel_size),
                (1, 1, stride1, stride1),
                [(0, 0), (0, 0), (0, 0), (0, 0)])
            planes.append(win[:, :, :top_h, :top_w] / norm)
    out = jnp.concatenate(planes, axis=1)
    return out.reshape(b, grid * grid, top_h, top_w)


# --------------------------------------------------------------- multibox --
def _corner_iou(a, b, plus_one=False):
    """IoU between (N,4) and (M,4) corner boxes -> (N, M).

    plus_one=True uses the integer-pixel convention (+1 on every
    extent, proposal.cc NonMaximumSuppression) — RPN boxes are pixel
    corners. The SSD family works on normalized [0,1] corners where
    the reference omits the +1."""
    add = 1.0 if plus_one else 0.0
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    inter = jnp.prod(jnp.clip(br - tl + add, 0.0, None), axis=-1)
    area_a = jnp.prod(jnp.clip(a[:, 2:] - a[:, :2] + add, 0.0, None),
                      axis=-1)
    area_b = jnp.prod(jnp.clip(b[:, 2:] - b[:, :2] + add, 0.0, None),
                      axis=-1)
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-12)


def _parse_floats(value, default):
    if value is None:
        return tuple(default)
    if isinstance(value, str):
        import ast
        value = ast.literal_eval(value)   # "(1,2)" strings from JSON attrs
    if not isinstance(value, (tuple, list)):
        value = (value,)
    return tuple(float(v) for v in value)


@register(name="_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",),
          differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """SSD anchor generator: (1, H*W*A, 4) corner boxes in [0, 1] units,
    A = len(sizes) + len(ratios) - 1 (size_i paired with ratios[0], then
    sizes[0] paired with each remaining ratio)."""
    sizes = _parse_floats(sizes, (1.0,))
    ratios = _parse_floats(ratios, (1.0,))
    steps = _parse_floats(steps, (-1.0, -1.0))
    offsets = _parse_floats(offsets, (0.5, 0.5))
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")

    half = []
    for s in sizes:
        r = ratios[0] ** 0.5
        half.append((s * r / 2.0, s / r / 2.0))
    for ratio in ratios[1:]:
        r = ratio ** 0.5
        half.append((sizes[0] * r / 2.0, sizes[0] / r / 2.0))

    boxes = []
    for hw, hh in half:
        boxes.append(jnp.stack(
            [cxg - hw, cyg - hh, cxg + hw, cyg + hh], axis=-1))
    out = jnp.stack(boxes, axis=2).reshape(1, h * w * len(half), 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


_VARIANCES = (0.1, 0.1, 0.2, 0.2)


def _encode_locs(anchors, matched_gt, variances):
    """Corner anchors + matched corner gts -> (dx, dy, dw, dh) targets."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    gw = jnp.maximum(matched_gt[:, 2] - matched_gt[:, 0], 1e-12)
    gh = jnp.maximum(matched_gt[:, 3] - matched_gt[:, 1], 1e-12)
    gcx = (matched_gt[:, 0] + matched_gt[:, 2]) / 2
    gcy = (matched_gt[:, 1] + matched_gt[:, 3]) / 2
    v0, v1, v2, v3 = variances
    return jnp.stack([
        (gcx - acx) / jnp.maximum(aw, 1e-12) / v0,
        (gcy - acy) / jnp.maximum(ah, 1e-12) / v1,
        jnp.log(gw / jnp.maximum(aw, 1e-12)) / v2,
        jnp.log(gh / jnp.maximum(ah, 1e-12)) / v3,
    ], axis=-1)


@register(name="_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",),
          differentiable=False, num_outputs=3)
def multibox_target(anchors, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=_VARIANCES):
    """SSD target matcher -> (loc_target (B, N*4), loc_mask (B, N*4),
    cls_target (B, N)).

    Matching follows the reference: every gt claims its best anchor
    (bipartite stage), then any anchor whose best-gt IoU clears
    overlap_threshold matches that gt. cls_target is gt class + 1, 0 for
    background; with negative mining, background anchors beyond
    ratio*num_pos with the smallest background-confidence deficit are
    ignored (ignore_label).
    """
    variances = _parse_floats(variances, _VARIANCES)
    anchors = anchors.reshape(-1, 4)
    num_anchors = anchors.shape[0]

    def one_sample(gts, scores):
        valid = gts[:, 0] >= 0
        iou = _corner_iou(anchors, gts[:, 1:5])          # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)

        # bipartite: each valid gt grabs its own argmax anchor
        best_anchor = jnp.argmax(iou, axis=0)            # (M,)
        forced_gt = jnp.full((num_anchors,), -1, jnp.int32)
        order = jnp.arange(gts.shape[0], dtype=jnp.int32)
        forced_gt = forced_gt.at[best_anchor].set(
            jnp.where(valid, order, forced_gt[best_anchor]))

        best_iou = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
        matched_gt = jnp.where(forced_gt >= 0, forced_gt,
                               jnp.where(best_iou >= overlap_threshold,
                                         best_gt, -1))
        is_pos = matched_gt >= 0
        gt_idx = jnp.clip(matched_gt, 0, gts.shape[0] - 1)
        cls_target = jnp.where(
            is_pos, gts[gt_idx, 0].astype(jnp.int32) + 1, 0)

        if negative_mining_ratio > 0:
            num_pos = jnp.sum(is_pos)
            max_neg = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                int(minimum_negative_samples))
            # mine the hardest backgrounds: smallest background-class
            # confidence margin first
            probs = jax.nn.softmax(scores, axis=0)       # (C+1, N)
            bg_conf = probs[0]
            candidate = (~is_pos) & (best_iou < negative_mining_thresh)
            hardness = jnp.where(candidate, 1.0 - bg_conf, -1.0)
            rank = jnp.argsort(jnp.argsort(-hardness))
            keep_neg = candidate & (rank < max_neg)
            cls_target = jnp.where(is_pos, cls_target,
                                   jnp.where(keep_neg, 0,
                                             jnp.int32(ignore_label)))

        loc = _encode_locs(anchors, gts[gt_idx, 1:5], variances)
        loc = jnp.where(is_pos[:, None], loc, 0.0)
        mask = jnp.where(is_pos[:, None],
                         jnp.ones((num_anchors, 4), jnp.float32), 0.0)
        return loc.reshape(-1), mask.reshape(-1), cls_target

    loc_t, loc_m, cls_t = jax.vmap(one_sample)(label, cls_pred)
    return loc_t, loc_m, cls_t.astype(jnp.float32)


def _decode_locs(anchors, deltas, variances):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    v0, v1, v2, v3 = variances
    cx = deltas[:, 0] * v0 * aw + acx
    cy = deltas[:, 1] * v1 * ah + acy
    w = jnp.exp(jnp.clip(deltas[:, 2] * v2, -10, 10)) * aw
    h = jnp.exp(jnp.clip(deltas[:, 3] * v3, -10, 10)) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def _greedy_nms_mask(boxes, scores, threshold, topk, plus_one=False):
    """Suppressed-flag vector via a fixed-trip greedy pass over the topk
    highest-scoring boxes. plus_one selects the pixel (+1) overlap
    convention (see _corner_iou)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    iou = _corner_iou(boxes[order], boxes[order], plus_one=plus_one)
    alive = scores[order] > -jnp.inf

    def body(i, alive):
        suppress = (iou[i] > threshold) & (jnp.arange(n) > i) & alive[i]
        return alive & ~suppress

    steps = n if topk < 0 else min(topk, n)
    alive = lax.fori_loop(0, steps, body, alive)
    inv = jnp.argsort(order)
    return alive[inv]


@register(name="_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",),
          differentiable=False)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=_VARIANCES, nms_topk=-1):
    """SSD decode + per-class NMS -> (B, N, 6) rows of
    [class_id, score, xmin, ymin, xmax, ymax], suppressed rows = -1.
    class_id is 0-based over foreground classes (background stripped),
    as the reference emits."""
    variances = _parse_floats(variances, _VARIANCES)
    if background_id != 0:
        raise NotImplementedError("background_id must be 0")
    anchors = anchor.reshape(-1, 4)

    def one_sample(probs, deltas):
        boxes = _decode_locs(anchors, deltas.reshape(-1, 4), variances)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        fg = probs[1:]                                  # strip background
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        nms_class = jnp.zeros_like(cls_id) if force_suppress else cls_id
        sc = jnp.where(keep, score, -jnp.inf)
        # class-aware NMS: boxes of different classes never overlap once
        # shifted apart by class index
        shifted = boxes + nms_class[:, None] * 4.0
        alive = _greedy_nms_mask(shifted, sc, nms_threshold, nms_topk)
        ok = keep & alive
        out = jnp.concatenate([
            jnp.where(ok, cls_id, -1.0)[:, None],
            jnp.where(ok, score, -1.0)[:, None],
            jnp.where(ok[:, None], boxes, -1.0)], axis=-1)
        # valid rows first, highest score first
        order = jnp.argsort(-out[:, 1])
        return out[order]

    return jax.vmap(one_sample)(cls_prob, loc_pred)


# ---------------------------------------------------------------- proposal --
@register(name="_contrib_Proposal", aliases=("Proposal",),
          differentiable=False)
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """Faster-RCNN RPN proposals: anchors + deltas -> clipped, size-
    filtered, NMS'd rois (B*post_nms, 5) [batch_idx, x1, y1, x2, y2]."""
    scales = _parse_floats(scales, (4, 8, 16, 32))
    ratios = _parse_floats(ratios, (0.5, 1, 2))
    b, two_a, h, w = cls_prob.shape
    num_anchors = len(scales) * len(ratios)

    # base anchors around (0, 0) at feature_stride, reference layout
    base = float(feature_stride)
    anchors = []
    for ratio in ratios:
        size = base * base
        ws = jnp.round(jnp.sqrt(size / ratio))
        hs = jnp.round(ws * ratio)
        for scale in scales:
            wsc, hsc = ws * scale, hs * scale
            cx = (base - 1) / 2.0
            cy = (base - 1) / 2.0
            anchors.append(jnp.stack([cx - (wsc - 1) / 2, cy - (hsc - 1) / 2,
                                      cx + (wsc - 1) / 2, cy + (hsc - 1) / 2]))
    base_anchors = jnp.stack(anchors)                     # (A, 4)

    sx = jnp.arange(w, dtype=jnp.float32) * feature_stride
    sy = jnp.arange(h, dtype=jnp.float32) * feature_stride
    syg, sxg = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([sxg, syg, sxg, syg], axis=-1)     # (H, W, 4)
    all_anchors = (shifts[:, :, None, :] +
                   base_anchors[None, None, :, :]).reshape(-1, 4)

    n = h * w * num_anchors
    pre = min(rpn_pre_nms_top_n, n)
    post = rpn_post_nms_top_n

    def one_sample(probs, deltas, info):
        fg = probs[num_anchors:].transpose(1, 2, 0).reshape(-1)
        dl = deltas.reshape(num_anchors, 4, h, w) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        widths = all_anchors[:, 2] - all_anchors[:, 0] + 1
        heights = all_anchors[:, 3] - all_anchors[:, 1] + 1
        ctr_x = all_anchors[:, 0] + (widths - 1) / 2
        ctr_y = all_anchors[:, 1] + (heights - 1) / 2
        cx = dl[:, 0] * widths + ctr_x
        cy = dl[:, 1] * heights + ctr_y
        bw = jnp.exp(jnp.clip(dl[:, 2], -10, 10)) * widths
        bh = jnp.exp(jnp.clip(dl[:, 3], -10, 10)) * heights
        boxes = jnp.stack([cx - (bw - 1) / 2, cy - (bh - 1) / 2,
                           cx + (bw - 1) / 2, cy + (bh - 1) / 2], axis=-1)
        im_h, im_w = info[0], info[1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, im_w - 1),
            jnp.clip(boxes[:, 1], 0, im_h - 1),
            jnp.clip(boxes[:, 2], 0, im_w - 1),
            jnp.clip(boxes[:, 3], 0, im_h - 1)], axis=-1)
        min_size = rpn_min_size * info[2]
        keep = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_size) &
                (boxes[:, 3] - boxes[:, 1] + 1 >= min_size))
        sc = jnp.where(keep, fg, -jnp.inf)
        top_sc, top_idx = lax.top_k(sc, pre)
        top_boxes = boxes[top_idx]
        # proposal.cc NMS overlaps use the integer-pixel +1 convention
        alive = _greedy_nms_mask(top_boxes, top_sc, threshold, -1,
                                 plus_one=True)
        final = jnp.where(alive, top_sc, -jnp.inf)
        sel_sc, sel = lax.top_k(final, min(post, pre))
        rois = top_boxes[sel]
        valid = sel_sc > -jnp.inf
        rois = jnp.where(valid[:, None], rois, 0.0)
        if rois.shape[0] < post:
            padn = post - rois.shape[0]
            rois = jnp.concatenate(
                [rois, jnp.zeros((padn, 4), rois.dtype)])
            sel_sc = jnp.concatenate(
                [sel_sc, jnp.full((padn,), -jnp.inf, sel_sc.dtype)])
        return rois, jnp.where(sel_sc == -jnp.inf, 0.0, sel_sc)

    rois, scores = jax.vmap(one_sample)(cls_prob, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(b, dtype=rois.dtype), post)
    out = jnp.concatenate([batch_idx[:, None],
                           rois.reshape(-1, 4)], axis=-1)
    if output_score:
        return out, scores.reshape(-1, 1)
    return out


# ------------------------------------------------------- graph sampling --
@register(name="_contrib_dgl_csr_neighbor_uniform_sample",
          differentiable=False, num_outputs="n", stateful_rng=True)
def dgl_csr_neighbor_uniform_sample(indptr, indices, *seeds,
                                    num_args=2, num_hops=1,
                                    num_neighbor=2, max_num_vertices=100,
                                    rng_key=None):
    """contrib/dgl_graph.cc uniform neighbor sampling over a CSR graph.

    Inputs are the CSR pieces (indptr, indices) plus one or more seed
    vertex arrays; per seed array returns a padded vertex id vector of
    length max_num_vertices whose first entry count is stored in its
    trailing element (the reference's layout for the sampled subgraph
    vertex list). Eager-only: sampling is data-dependent.
    """
    import numpy as onp
    indptr_np = onp.asarray(indptr).astype(onp.int64)
    indices_np = onp.asarray(indices).astype(onp.int64)
    if rng_key is not None:
        try:
            seed_bits = onp.asarray(jax.random.key_data(rng_key)).ravel()
        except Exception:
            seed_bits = onp.asarray(rng_key).ravel()
        seed = int(onp.uint32(seed_bits[-1]))
    else:
        seed = 0
    rng = onp.random.RandomState(seed)
    cap = int(max_num_vertices) - 1
    outs = []
    for seed_arr in seeds:
        frontier = [int(v) for v in onp.asarray(seed_arr).ravel()
                    if v >= 0]
        visited = list(dict.fromkeys(frontier))[:cap]
        seen = set(visited)
        for _ in range(int(num_hops)):
            if len(visited) >= cap:
                break           # cap during sampling, not after
            nxt = []
            for v in frontier:
                lo, hi = indptr_np[v], indptr_np[v + 1]
                neigh = indices_np[lo:hi]
                if len(neigh) > num_neighbor:
                    neigh = rng.choice(neigh, size=int(num_neighbor),
                                       replace=False)
                nxt.extend(int(u) for u in neigh)
            fresh = []
            for u in dict.fromkeys(nxt):
                if u not in seen:
                    seen.add(u)
                    fresh.append(u)
                    if len(visited) + len(fresh) >= cap:
                        break
            visited.extend(fresh)
            frontier = fresh
        out = onp.full((max_num_vertices,), -1, onp.int64)
        out[:len(visited)] = visited
        out[-1] = len(visited)
        outs.append(jnp.asarray(out))
    return outs


@register(name="_contrib_dgl_subgraph", differentiable=False,
          num_outputs="n")
def dgl_subgraph(indptr, indices, *vertex_sets, return_mapping=False):
    """contrib/dgl_graph.cc vertex-induced subgraph extraction: for each
    vertex set, the CSR (indptr, indices) of the induced subgraph with
    vertices renumbered by their position in the set. Eager-only."""
    if return_mapping:
        raise NotImplementedError(
            "dgl_subgraph return_mapping=True (original edge ids) is not "
            "implemented; call with return_mapping=False")
    import numpy as onp
    indptr_np = onp.asarray(indptr).astype(onp.int64)
    indices_np = onp.asarray(indices).astype(onp.int64)
    outs = []
    for vset in vertex_sets:
        verts = [int(v) for v in onp.asarray(vset).ravel() if v >= 0]
        remap = {v: i for i, v in enumerate(verts)}
        sub_indptr = [0]
        sub_indices = []
        for v in verts:
            for u in indices_np[indptr_np[v]:indptr_np[v + 1]]:
                if int(u) in remap:
                    sub_indices.append(remap[int(u)])
            sub_indptr.append(len(sub_indices))
        outs.append(jnp.asarray(onp.asarray(sub_indptr, onp.int64)))
        outs.append(jnp.asarray(onp.asarray(sub_indices, onp.int64)))
    return outs


@register(name="_contrib_MultiProposal", aliases=("MultiProposal",),
          differentiable=False)
def multi_proposal(cls_prob, bbox_pred, im_info, **kwargs):
    """contrib/multi_proposal.cc — batched Proposal. The Proposal op here
    already vmaps over the batch, so MultiProposal shares it."""
    return proposal(cls_prob, bbox_pred, im_info, **kwargs)


@register(name="_contrib_PSROIPooling", aliases=("PSROIPooling",))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                  pooled_size=1, group_size=0):
    """R-FCN position-sensitive ROI pooling (contrib/psroi_pooling.cc).
    Implemented as the no-offset case of the deformable variant: each
    bin averages a fixed bilinear sample grid instead of enumerating
    integer pixels — same estimator, static shapes for XLA."""
    return deformable_psroi_pooling(
        data, rois, None, spatial_scale=spatial_scale,
        output_dim=output_dim, group_size=group_size or pooled_size,
        pooled_size=pooled_size, sample_per_part=2, no_trans=True)


# ------------------------------------------------------------- deformable --
def _bilinear_gather(img, ys, xs):
    """Sample img (C, H, W) at float coords (ys, xs) of any shape ->
    (C,) + coord shape. Out-of-bounds contributions are zero, matching
    the reference's deformable_im2col boundary handling."""
    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    out = 0.0
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yy = y0 + dy
            xx = x0 + dx
            inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            vals = img[:, yi, xi]
            out = out + vals * (wy * wx * inb)[None]
    return out


@register(name="_contrib_DeformableConvolution",
          aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                           num_filter=1, num_group=1, num_deformable_group=1,
                           no_bias=False, workspace=1024, layout="NCHW"):
    """DCNv1: each kernel tap samples the input at its learned offset via
    bilinear interpolation, then an ordinary dense contraction applies
    the weights (one einsum onto the MXU instead of im2col + GEMM)."""
    b, c, h, w = data.shape
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    out_h = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    out_w = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    dg = num_deformable_group
    cg = c // dg

    oy = jnp.arange(out_h, dtype=jnp.float32) * sh - ph
    ox = jnp.arange(out_w, dtype=jnp.float32) * sw - pw
    oyg, oxg = jnp.meshgrid(oy, ox, indexing="ij")       # (Ho, Wo)

    off = offset.reshape(b, dg, kh * kw, 2, out_h, out_w)

    def sample_one(img, offs):
        # img (C, H, W); offs (dg, K*K, 2, Ho, Wo)
        cols = []
        for k in range(kh * kw):
            ky, kx = divmod(k, kw)
            base_y = oyg + ky * dh
            base_x = oxg + kx * dw
            per_group = []
            for g in range(dg):
                ys = base_y + offs[g, k, 0]
                xs = base_x + offs[g, k, 1]
                per_group.append(
                    _bilinear_gather(img[g * cg:(g + 1) * cg], ys, xs))
            cols.append(jnp.concatenate(per_group, axis=0))
        return jnp.stack(cols, axis=1)                   # (C, K*K, Ho, Wo)

    cols = jax.vmap(sample_one)(data, off)               # (B, C, KK, Ho, Wo)
    wmat = weight.reshape(num_filter, c // num_group, kh * kw)
    if num_group == 1:
        out = jnp.einsum("bckhw,ock->bohw", cols, wmat)
    else:
        cols_g = cols.reshape(b, num_group, c // num_group, kh * kw,
                              out_h, out_w)
        wg = wmat.reshape(num_group, num_filter // num_group,
                          c // num_group, kh * kw)
        out = jnp.einsum("bgckhw,gock->bgohw", cols_g, wg) \
            .reshape(b, num_filter, out_h, out_w)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register(name="_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",))
def deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                             output_dim=1, group_size=1, pooled_size=1,
                             part_size=0, sample_per_part=1, trans_std=0.0,
                             no_trans=False):
    """R-FCN position-sensitive ROI pooling with optional learned part
    offsets. data channels = output_dim * group_size^2 (ctop-major, the
    reference layout); each pooled bin (ph, pw) averages
    sample_per_part^2 bilinear samples from its position-sensitive
    channel slice. Part offsets are class-dependent exactly as in the
    reference (deformable_psroi_pooling.cc:117): trans carries
    num_classes = trans_channels/2 offset pairs, and output channel
    ctop uses pair ctop // channels_each_class — per-class (dx, dy)
    per bin, not one shared offset."""
    part_size = part_size or pooled_size
    b, c, h, w = data.shape
    ps = pooled_size
    g = group_size

    if trans is None or no_trans:
        num_classes = 1
        trans2 = jnp.zeros((rois.shape[0], 2, part_size, part_size),
                           data.dtype)
    else:
        tch = 1
        for d in trans.shape[1:]:
            tch *= int(d)
        tch //= part_size * part_size
        if tch < 2 or tch % 2:
            raise ValueError(
                "deformable_psroi_pooling: trans must carry an even "
                "number of offset channels (got %d)" % tch)
        num_classes = tch // 2
        if output_dim % num_classes:
            raise ValueError(
                "deformable_psroi_pooling: output_dim (%d) must be a "
                "multiple of the trans class count (%d)"
                % (output_dim, num_classes))
        trans2 = trans.reshape(
            rois.shape[0], num_classes * 2, part_size, part_size)
    cec = output_dim // num_classes          # channels_each_class

    def one_roi(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - 0.5
        y1 = roi[2] * spatial_scale - 0.5
        x2 = (roi[3] + 1.0) * spatial_scale - 0.5
        y2 = (roi[4] + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / ps
        bin_h = rh / ps
        img = data[bidx]

        out = jnp.zeros((output_dim, ps, ps), data.dtype)
        for phi in range(ps):
            for pwi in range(ps):
                gy = min(phi * g // ps, g - 1)
                gx = min(pwi * g // ps, g - 1)
                for cls in range(num_classes):
                    if no_trans:
                        off_x = off_y = 0.0
                    else:
                        pidx_y = phi * part_size // ps
                        pidx_x = pwi * part_size // ps
                        # reference class selection
                        # (deformable_psroi_pooling.cc:117): offset
                        # pair = ctop // channels_each_class, x channel
                        # first then y
                        off_x = tr[2 * cls, pidx_y, pidx_x] \
                            * trans_std * rw
                        off_y = tr[2 * cls + 1, pidx_y, pidx_x] \
                            * trans_std * rh
                    ys = y1 + phi * bin_h + off_y + \
                        (jnp.arange(sample_per_part) + 0.5) * \
                        (bin_h / sample_per_part)
                    xs = x1 + pwi * bin_w + off_x + \
                        (jnp.arange(sample_per_part) + 0.5) * \
                        (bin_w / sample_per_part)
                    ysg, xsg = jnp.meshgrid(ys, xs, indexing="ij")
                    # reference channel layout (psroi_pooling.cc:98,
                    # deformable_psroi_pooling.cc:136): input channel
                    # (ctop*G + gh)*G + gw — ctop-major, so ported
                    # R-FCN weights keep their meaning
                    slice_ = img.reshape(output_dim, g * g, h, w)[
                        cls * cec:(cls + 1) * cec, gy * g + gx]
                    # reference border rule
                    # (deformable_psroi_pooling.cc): samples beyond
                    # half a pixel outside the map are SKIPPED (bin
                    # average divides by the in-bounds count, 0 when
                    # none); the rest are clamped to the map before
                    # bilinear sampling — without this, border-ROI
                    # outputs are attenuated by the fixed divisor
                    inb = ((ysg >= -0.5) & (ysg <= h - 0.5)
                           & (xsg >= -0.5) & (xsg <= w - 0.5))
                    ysc = jnp.clip(ysg, 0.0, h - 1.0)
                    xsc = jnp.clip(xsg, 0.0, w - 1.0)
                    vals = _bilinear_gather(slice_, ysc, xsc) \
                        * inb[None]
                    cnt = jnp.maximum(inb.sum(), 1)
                    out = out.at[cls * cec:(cls + 1) * cec,
                                 phi, pwi].set(
                        vals.sum(axis=(1, 2)) / cnt)
        return out

    return jax.vmap(one_roi)(rois, trans2)


@register(name="_contrib_edge_id", differentiable=False)
def edge_id(indptr, indices, data, u, v):
    """contrib/dgl_graph.cc `_contrib_edge_id`: out[i] = edge value stored
    at (u[i], v[i]) in the CSR graph, -1 when absent. The reference takes
    one CSR NDArray; on TPU the CSR pieces arrive as three dense inputs
    (same convention as the other graph ops here). Eager-only."""
    import numpy as onp
    ip = onp.asarray(indptr).astype(onp.int64)
    ix = onp.asarray(indices).astype(onp.int64)
    dat = onp.asarray(data)
    uu = onp.asarray(u).astype(onp.int64).ravel()
    vv = onp.asarray(v).astype(onp.int64).ravel()
    out = onp.full(uu.shape, -1, dat.dtype)
    for i, (a, b) in enumerate(zip(uu, vv)):
        lo, hi = ip[a], ip[a + 1]
        hit = onp.nonzero(ix[lo:hi] == b)[0]
        if hit.size:
            out[i] = dat[lo + hit[0]]
    return jnp.asarray(out)


@register(name="_contrib_dgl_adjacency", differentiable=False)
def dgl_adjacency(data):
    """CSR edge-id values -> adjacency ones (float32); structure (indptr/
    indices) passes through outside the op."""
    return jnp.ones_like(data, dtype=jnp.float32)


@register(name="_contrib_getnnz", differentiable=False)
def getnnz(indptr, indices, axis=None, num_cols=0):
    """Number of stored values of a CSR graph: total (axis=None), per-row
    (axis=1), or per-column (axis=0, needs num_cols when the graph has
    trailing empty columns). Eager-only host op."""
    import numpy as onp
    ip = onp.asarray(indptr).astype(onp.int64)
    ix = onp.asarray(indices).astype(onp.int64)
    if axis is None:
        return jnp.asarray(onp.asarray([ix.shape[0]], onp.int64))
    if axis == 1:
        return jnp.asarray(ip[1:] - ip[:-1])
    n = int(num_cols) or (int(ix.max()) + 1 if ix.size else 0)
    return jnp.asarray(onp.bincount(ix, minlength=n).astype(onp.int64))


@register(name="_contrib_dgl_csr_neighbor_non_uniform_sample",
          differentiable=False, num_outputs="n", stateful_rng=True)
def dgl_csr_neighbor_non_uniform_sample(indptr, indices, probability, *seeds,
                                        num_args=3, num_hops=1,
                                        num_neighbor=2,
                                        max_num_vertices=100, rng_key=None):
    """Weighted variant of the uniform sampler: neighbors are drawn
    without replacement with probability proportional to
    `probability[vertex]`. Same padded-vertex-vector output layout."""
    import numpy as onp
    indptr_np = onp.asarray(indptr).astype(onp.int64)
    indices_np = onp.asarray(indices).astype(onp.int64)
    prob = onp.asarray(probability).astype(onp.float64).ravel()
    if rng_key is not None:
        try:
            seed_bits = onp.asarray(jax.random.key_data(rng_key)).ravel()
        except Exception:
            seed_bits = onp.asarray(rng_key).ravel()
        seed = int(onp.uint32(seed_bits[-1]))
    else:
        seed = 0
    rng = onp.random.RandomState(seed)
    cap = int(max_num_vertices) - 1
    outs = []
    for seed_arr in seeds:
        frontier = [int(v) for v in onp.asarray(seed_arr).ravel() if v >= 0]
        visited = list(dict.fromkeys(frontier))[:cap]
        seen = set(visited)
        for _ in range(int(num_hops)):
            if len(visited) >= cap:
                break
            nxt = []
            for vtx in frontier:
                lo, hi = indptr_np[vtx], indptr_np[vtx + 1]
                neigh = indices_np[lo:hi]
                if len(neigh) > num_neighbor:
                    p = prob[neigh]
                    tot = p.sum()
                    if tot > 0:
                        nz = int((p > 0).sum())
                        if nz <= num_neighbor:
                            # fewer positive-weight neighbors than requested:
                            # take exactly those (choice would raise)
                            neigh = neigh[p > 0]
                        else:
                            neigh = rng.choice(neigh, size=int(num_neighbor),
                                               replace=False, p=p / tot)
                    else:
                        neigh = rng.choice(neigh, size=int(num_neighbor),
                                           replace=False)
                nxt.extend(int(x) for x in neigh)
            fresh = []
            for x in dict.fromkeys(nxt):
                if x not in seen:
                    seen.add(x)
                    fresh.append(x)
                    if len(visited) + len(fresh) >= cap:
                        break
            visited.extend(fresh)
            frontier = fresh
        out = onp.full((max_num_vertices,), -1, onp.int64)
        out[:len(visited)] = visited
        out[-1] = len(visited)
        outs.append(jnp.asarray(out))
    return outs


@register(name="_contrib_dgl_graph_compact", differentiable=False,
          num_outputs="n")
def dgl_graph_compact(*args, graph_sizes=(), return_mapping=False,
                      num_args=None):
    """contrib/dgl_graph.cc `_contrib_dgl_graph_compact`: drop the empty
    trailing rows/columns a sampled sub-CSR carries and renumber vertices
    by their position in the sampled vertex list. Inputs arrive as
    (indptr, indices, data, vertices) quadruples per graph — the CSR-
    pieces convention used by all graph ops here. Eager-only."""
    if return_mapping:
        raise NotImplementedError(
            "dgl_graph_compact return_mapping=True is not implemented")
    import numpy as onp
    if isinstance(graph_sizes, int):
        graph_sizes = (graph_sizes,)
    quads = [args[i:i + 4] for i in range(0, len(args), 4)]
    outs = []
    for k, (indptr, indices, data, verts) in enumerate(quads):
        ip = onp.asarray(indptr).astype(onp.int64)
        ix = onp.asarray(indices).astype(onp.int64)
        dat = onp.asarray(data)
        size = int(graph_sizes[k]) if k < len(graph_sizes) else \
            int(onp.asarray(verts).ravel()[-1])
        vs = [int(v) for v in onp.asarray(verts).ravel()[:size]]
        remap = {v: i for i, v in enumerate(vs)}
        new_ip = [0]
        new_ix = []
        new_dat = []
        for v in vs:
            for j in range(int(ip[v]), int(ip[v + 1])):
                col = int(ix[j])
                if col in remap:
                    new_ix.append(remap[col])
                    new_dat.append(dat[j])
            new_ip.append(len(new_ix))
        outs.append(jnp.asarray(onp.asarray(new_ip, onp.int64)))
        outs.append(jnp.asarray(onp.asarray(new_ix, onp.int64)))
        outs.append(jnp.asarray(onp.asarray(new_dat, dat.dtype)))
    return outs
