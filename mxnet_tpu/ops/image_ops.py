"""Image operators — nd.image.* / sym.image.*.

Reference: src/operator/image/image_random.cc (registers _image_to_tensor,
_image_normalize, flips, brightness/contrast/saturation/hue jitter,
lighting), resize.cc (_image_resize), crop.cc (_image_crop). These back the
Gluon vision transforms so that the transforms stay hybridizable: every op
exists in both the ndarray and symbol namespaces.

TPU-native notes: all ops are pure jnp functions (batch-friendly, fused by
XLA); random augmentations take an explicit threefry key (`rng_key`) like
every other sampler here instead of a per-resource Philox state.
Layout follows the reference: to_tensor consumes HWC (or NHWC) uint8-like
input and produces CHW float32; normalize consumes CHW/NCHW.
"""

import jax
import jax.numpy as jnp

from . import register


def _is_batched(x, rank):
    return x.ndim == rank + 1


@register(name="_image_to_tensor", aliases=("image_to_tensor",))
def image_to_tensor(x):
    """HWC (or NHWC) [0,255] -> CHW (NCHW) float32 [0,1]."""
    perm = (0, 3, 1, 2) if _is_batched(x, 3) else (2, 0, 1)
    return jnp.transpose(x, perm).astype(jnp.float32) / 255.0


@register(name="_image_normalize", aliases=("image_normalize",))
def image_normalize(x, mean=0.0, std=1.0):
    """Channel-wise (x - mean) / std on CHW or NCHW float input."""
    mean = jnp.reshape(jnp.asarray(mean, x.dtype), (-1, 1, 1))
    std = jnp.reshape(jnp.asarray(std, x.dtype), (-1, 1, 1))
    return (x - mean) / std


@register(name="_image_flip_left_right", aliases=("image_flip_left_right",))
def image_flip_left_right(x):
    """Flip HWC (or NHWC) image along width."""
    return jnp.flip(x, axis=-2)


@register(name="_image_flip_top_bottom", aliases=("image_flip_top_bottom",))
def image_flip_top_bottom(x):
    return jnp.flip(x, axis=-3)


@register(name="_image_random_flip_left_right",
          aliases=("image_random_flip_left_right",), stateful_rng=True)
def image_random_flip_left_right(x, rng_key=None):
    flip = jax.random.bernoulli(rng_key)
    return jnp.where(flip, jnp.flip(x, axis=-2), x)


@register(name="_image_random_flip_top_bottom",
          aliases=("image_random_flip_top_bottom",), stateful_rng=True)
def image_random_flip_top_bottom(x, rng_key=None):
    flip = jax.random.bernoulli(rng_key)
    return jnp.where(flip, jnp.flip(x, axis=-3), x)


@register(name="_image_resize", aliases=("image_resize",))
def image_resize(x, size=None, keep_ratio=False, interp=1):
    """Resize HWC (or NHWC) to `size` (int or (w, h)); bilinear when
    interp=1, nearest when interp=0. keep_ratio scales the short side to
    `size` (static-shape variant of the reference's resize_short)."""
    h, w = (x.shape[-3], x.shape[-2])
    if isinstance(size, int):
        if keep_ratio:
            if h < w:
                new_h, new_w = size, max(1, int(round(w * size / h)))
            else:
                new_h, new_w = max(1, int(round(h * size / w))), size
        else:
            new_h = new_w = size
    else:
        new_w, new_h = size  # reference order: (w, h)
    method = "nearest" if interp == 0 else "bilinear"
    if _is_batched(x, 3):
        shape = (x.shape[0], new_h, new_w, x.shape[3])
    else:
        shape = (new_h, new_w, x.shape[2])
    out = jax.image.resize(x.astype(jnp.float32), shape, method=method)
    return out.astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.integer) \
        else out


@register(name="_image_crop", aliases=("image_crop",))
def image_crop(x, x0=0, y0=0, width=0, height=0):
    """Static crop of HWC (or NHWC): rows [y0, y0+height), cols
    [x0, x0+width)."""
    if _is_batched(x, 3):
        return x[:, y0:y0 + height, x0:x0 + width, :]
    return x[y0:y0 + height, x0:x0 + width, :]


def _blend(a, b, alpha):
    return a * alpha + b * (1.0 - alpha)


def _grayscale(x):
    # ITU-R BT.601 luma weights over the channel axis of HWC/NHWC
    w = jnp.asarray([0.299, 0.587, 0.114], x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


@register(name="_image_random_brightness",
          aliases=("image_random_brightness",), stateful_rng=True)
def image_random_brightness(x, max_brightness=0.0, rng_key=None):
    alpha = 1.0 + jax.random.uniform(
        rng_key, minval=-max_brightness, maxval=max_brightness)
    return x * alpha


@register(name="_image_random_contrast",
          aliases=("image_random_contrast",), stateful_rng=True)
def image_random_contrast(x, max_contrast=0.0, rng_key=None):
    alpha = 1.0 + jax.random.uniform(
        rng_key, minval=-max_contrast, maxval=max_contrast)
    gray_mean = jnp.mean(_grayscale(x))
    return _blend(x, gray_mean, alpha)


@register(name="_image_random_saturation",
          aliases=("image_random_saturation",), stateful_rng=True)
def image_random_saturation(x, max_saturation=0.0, rng_key=None):
    alpha = 1.0 + jax.random.uniform(
        rng_key, minval=-max_saturation, maxval=max_saturation)
    return _blend(x, _grayscale(x), alpha)


@register(name="_image_random_hue", aliases=("image_random_hue",),
          stateful_rng=True)
def image_random_hue(x, max_hue=0.0, rng_key=None):
    """Hue rotation via the YIQ approximation the reference uses
    (image_random-inl.h RandomHue)."""
    alpha = jax.random.uniform(rng_key, minval=-max_hue, maxval=max_hue)
    u, w = jnp.cos(alpha * jnp.pi), jnp.sin(alpha * jnp.pi)
    t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], x.dtype)
    t_rgb = jnp.asarray([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]], x.dtype)
    rot = jnp.asarray([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], x.dtype)
    m = t_rgb @ rot @ t_yiq
    return jnp.einsum("...c,dc->...d", x, m)


@register(name="_image_random_color_jitter",
          aliases=("image_random_color_jitter",), stateful_rng=True)
def image_random_color_jitter(x, brightness=0.0, contrast=0.0,
                              saturation=0.0, hue=0.0, rng_key=None):
    kb, kc, ks, kh = jax.random.split(rng_key, 4)
    if brightness:
        x = image_random_brightness(x, brightness, rng_key=kb)
    if contrast:
        x = image_random_contrast(x, contrast, rng_key=kc)
    if saturation:
        x = image_random_saturation(x, saturation, rng_key=ks)
    if hue:
        x = image_random_hue(x, hue, rng_key=kh)
    return x


# PCA lighting noise over ImageNet eigen-basis (AlexNet augmentation;
# reference image_random-inl.h AdjustLighting / RandomLighting).
_EIGVAL = (55.46, 4.794, 1.148)
_EIGVEC = ((-0.5675, 0.7192, 0.4009),
           (-0.5808, -0.0045, -0.8140),
           (-0.5836, -0.6948, 0.4203))


@register(name="_image_adjust_lighting", aliases=("image_adjust_lighting",))
def image_adjust_lighting(x, alpha=(0.0, 0.0, 0.0)):
    vec = jnp.asarray(_EIGVEC, x.dtype)
    val = jnp.asarray(_EIGVAL, x.dtype) * jnp.asarray(alpha, x.dtype)
    return x + vec @ val


@register(name="_image_random_lighting",
          aliases=("image_random_lighting",), stateful_rng=True)
def image_random_lighting(x, alpha_std=0.05, rng_key=None):
    alpha = jax.random.normal(rng_key, (3,), x.dtype) * alpha_std
    vec = jnp.asarray(_EIGVEC, x.dtype)
    val = jnp.asarray(_EIGVAL, x.dtype) * alpha
    return x + vec @ val
