"""Random sampling operators.

Reference: src/operator/random/ (sample_op.cc samplers, multisample_op.cc
distribution-parameter sampling, pdf ops). TPU-native: counter-based
threefry keys from jax.random instead of per-resource Philox generator
state — functional keys are what make RNG reproducible under jit/pjit
(SURVEY §7 hard part (f) documents the divergence).
"""

import jax
import jax.numpy as jnp

from . import register


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register(name="_random_uniform", aliases=("uniform", "random_uniform"),
          differentiable=False, stateful_rng=True)
def random_uniform(low=0.0, high=1.0, shape=(), dtype="float32", rng_key=None):
    return jax.random.uniform(rng_key, _shape(shape), dtype=jnp.dtype(dtype),
                              minval=low, maxval=high)


@register(name="_random_normal", aliases=("normal", "random_normal"),
          differentiable=False, stateful_rng=True)
def random_normal(loc=0.0, scale=1.0, shape=(), dtype="float32", rng_key=None):
    return loc + scale * jax.random.normal(rng_key, _shape(shape), dtype=jnp.dtype(dtype))


@register(name="_random_gamma", aliases=("gamma_sample", "random_gamma"),
          differentiable=False, stateful_rng=True)
def random_gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", rng_key=None):
    return beta * jax.random.gamma(rng_key, alpha, _shape(shape), dtype=jnp.dtype(dtype))


@register(name="_random_exponential", aliases=("random_exponential", "exponential"),
          differentiable=False, stateful_rng=True)
def random_exponential(lam=1.0, shape=(), dtype="float32", rng_key=None):
    return jax.random.exponential(rng_key, _shape(shape), dtype=jnp.dtype(dtype)) / lam


@register(name="_random_poisson", aliases=("random_poisson", "poisson"),
          differentiable=False, stateful_rng=True)
def random_poisson(lam=1.0, shape=(), dtype="float32", rng_key=None):
    return jax.random.poisson(rng_key, lam, _shape(shape)).astype(jnp.dtype(dtype))


@register(name="_random_negative_binomial", aliases=("random_negative_binomial",),
          differentiable=False, stateful_rng=True)
def random_negative_binomial(k=1, p=1.0, shape=(), dtype="float32", rng_key=None):
    k1, k2 = jax.random.split(rng_key)
    lam = jax.random.gamma(k1, k, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, _shape(shape)).astype(jnp.dtype(dtype))


@register(name="_random_generalized_negative_binomial",
          aliases=("random_generalized_negative_binomial",),
          differentiable=False, stateful_rng=True)
def random_gen_neg_binomial(mu=1.0, alpha=1.0, shape=(), dtype="float32", rng_key=None):
    k1, k2 = jax.random.split(rng_key)
    g = jax.random.gamma(k1, 1.0 / alpha, _shape(shape)) * (alpha * mu)
    return jax.random.poisson(k2, g, _shape(shape)).astype(jnp.dtype(dtype))


@register(name="_random_randint", aliases=("randint",), differentiable=False,
          stateful_rng=True)
def random_randint(low=0, high=1, shape=(), dtype="int32", rng_key=None):
    return jax.random.randint(rng_key, _shape(shape), low, high, dtype=jnp.dtype(dtype))


@register(name="_sample_multinomial", aliases=("sample_multinomial", "multinomial"),
          differentiable=False, stateful_rng=True)
def sample_multinomial(data, shape=(), get_prob=False, dtype="int32", rng_key=None):
    n = 1
    for s in _shape(shape):
        n *= s
    n = max(n, 1)
    logits = jnp.log(jnp.maximum(data, 1e-37))
    out = jax.random.categorical(rng_key, logits, axis=-1,
                                 shape=(n,) + data.shape[:-1])
    if data.ndim == 1:
        out = out.reshape(_shape(shape) or ())
    else:
        out = jnp.moveaxis(out, 0, -1).reshape(data.shape[:-1] + _shape(shape))
    return out.astype(jnp.dtype(dtype))


@register(name="_sample_unique_zipfian", differentiable=False, stateful_rng=True)
def sample_unique_zipfian(range_max=1, shape=(), rng_key=None):
    """Log-uniform (zipfian) candidate sampling, UNIQUE within each row
    (sample_op.cc SampleUniqueZipfian: rejection until distinct).
    Duplicate positions are resampled in a bounded while_loop — static
    shapes, so it stays jittable."""
    from jax import lax
    shp = _shape(shape)
    n = shp[-1] if shp else 1
    batch = shp[:-1] if shp else ()

    def draw(k, s):
        u = jax.random.uniform(k, s)
        return (jnp.exp(u * jnp.log(range_max + 1.0)) - 1.0).astype(
            jnp.int32)

    def dup_mask(v):
        # True at every position holding a value already seen in-row
        order = jnp.argsort(v, axis=-1)
        sv = jnp.take_along_axis(v, order, -1)
        dups = jnp.concatenate(
            [jnp.zeros(sv.shape[:-1] + (1,), bool),
             sv[..., 1:] == sv[..., :-1]], axis=-1)
        return jnp.put_along_axis(jnp.zeros_like(dups), order, dups, -1,
                                  inplace=False)

    # the mask rides in the loop state so each iteration pays ONE
    # argsort pass (cond reads it, body consumes it and computes the
    # next iteration's)
    def cond(state):
        _, mask, _, i = state
        return jnp.any(mask) & (i < 64)

    def body(state):
        v, mask, k, i = state
        k, sub = jax.random.split(k)
        v = jnp.where(mask, draw(sub, v.shape), v)
        return v, dup_mask(v), k, i + 1

    v0 = draw(rng_key, batch + (n,))
    v, _, _, _ = lax.while_loop(cond, body, (v0, dup_mask(v0), rng_key, 0))
    return v.reshape(shp or ()).astype("int64")


# Distribution-parameter tensor sampling (src/operator/random/multisample_op.cc)
@register(name="sample_uniform", aliases=("_sample_uniform",),
          differentiable=False, stateful_rng=True)
def sample_uniform(low, high, shape=(), dtype="float32", rng_key=None):
    s = _shape(shape)
    u = jax.random.uniform(rng_key, low.shape + s, dtype=jnp.dtype(dtype))
    return low.reshape(low.shape + (1,) * len(s)) + \
        (high - low).reshape(low.shape + (1,) * len(s)) * u


@register(name="sample_normal", aliases=("_sample_normal",),
          differentiable=False, stateful_rng=True)
def sample_normal(mu, sigma, shape=(), dtype="float32", rng_key=None):
    s = _shape(shape)
    z = jax.random.normal(rng_key, mu.shape + s, dtype=jnp.dtype(dtype))
    return mu.reshape(mu.shape + (1,) * len(s)) + \
        sigma.reshape(sigma.shape + (1,) * len(s)) * z


@register(name="sample_gamma", aliases=("_sample_gamma",),
          differentiable=False, stateful_rng=True)
def sample_gamma(alpha, beta, shape=(), dtype="float32", rng_key=None):
    s = _shape(shape)
    a = alpha.reshape(alpha.shape + (1,) * len(s))
    g = jax.random.gamma(rng_key, a, a.shape[:len(alpha.shape)] + s,
                         dtype=jnp.dtype(dtype))
    return g * beta.reshape(beta.shape + (1,) * len(s))


@register(name="sample_exponential", aliases=("_sample_exponential",),
          differentiable=False, stateful_rng=True)
def sample_exponential(lam, shape=(), dtype="float32", rng_key=None):
    s = _shape(shape)
    e = jax.random.exponential(rng_key, lam.shape + s, dtype=jnp.dtype(dtype))
    return e / lam.reshape(lam.shape + (1,) * len(s))


@register(name="sample_poisson", aliases=("_sample_poisson",),
          differentiable=False, stateful_rng=True)
def sample_poisson(lam, shape=(), dtype="float32", rng_key=None):
    s = _shape(shape)
    p = jax.random.poisson(rng_key, lam.reshape(lam.shape + (1,) * len(s)),
                           lam.shape + s)
    return p.astype(jnp.dtype(dtype))


@register(name="sample_negative_binomial", aliases=("_sample_negative_binomial",),
          differentiable=False, stateful_rng=True)
def sample_negative_binomial(k, p, shape=(), dtype="float32", rng_key=None):
    s = _shape(shape)
    k1, k2 = jax.random.split(rng_key)
    kk = k.reshape(k.shape + (1,) * len(s))
    pp = p.reshape(p.shape + (1,) * len(s))
    lam = jax.random.gamma(k1, kk, k.shape + s) * ((1 - pp) / pp)
    return jax.random.poisson(k2, lam, k.shape + s).astype(jnp.dtype(dtype))


@register(name="sample_generalized_negative_binomial",
          aliases=("_sample_generalized_negative_binomial",),
          differentiable=False, stateful_rng=True)
def sample_gen_negative_binomial(mu, alpha, shape=(), dtype="float32",
                                 rng_key=None):
    s = _shape(shape)
    k1, k2 = jax.random.split(rng_key)
    m = mu.reshape(mu.shape + (1,) * len(s))
    a = alpha.reshape(alpha.shape + (1,) * len(s))
    g = jax.random.gamma(k1, 1.0 / a, mu.shape + s) * (a * m)
    return jax.random.poisson(k2, g, mu.shape + s).astype(jnp.dtype(dtype))


# --------------------------------------------------------------- pdf ops --
# Reference: src/operator/random/pdf_op.{cc,h} — per-sample (log-)density
# given a leading batch of distribution parameters. Parameter tensors have
# shape (s...); sample adds a trailing draws axis (s..., m). Gradients come
# from jax.vjp on the closed-form log-density instead of the hand-written
# PDF_*_Grad kernels.
def _pbc(parm, sample):
    """Broadcast a parameter tensor against the sample's trailing draw axis."""
    return parm[..., None] if sample.ndim > parm.ndim else parm


@register(name="_random_pdf_uniform", aliases=("pdf_uniform",))
def pdf_uniform(sample, low, high, is_log=False):
    low, high = _pbc(low, sample), _pbc(high, sample)
    inside = (sample >= low) & (sample <= high)
    out = jnp.where(inside, 1.0 / (high - low), 0.0)
    return jnp.log(out) if is_log else out


@register(name="_random_pdf_normal", aliases=("pdf_normal",))
def pdf_normal(sample, mu, sigma, is_log=False):
    mu, sigma = _pbc(mu, sample), _pbc(sigma, sample)
    logp = -0.5 * jnp.square((sample - mu) / sigma) - jnp.log(
        sigma * jnp.sqrt(2 * jnp.pi))
    return logp if is_log else jnp.exp(logp)


@register(name="_random_pdf_gamma", aliases=("pdf_gamma",))
def pdf_gamma(sample, alpha, beta, is_log=False):
    a, b = _pbc(alpha, sample), _pbc(beta, sample)
    logp = a * jnp.log(b) + (a - 1) * jnp.log(sample) - b * sample \
        - jax.scipy.special.gammaln(a)
    return logp if is_log else jnp.exp(logp)


@register(name="_random_pdf_exponential", aliases=("pdf_exponential",))
def pdf_exponential(sample, lam, is_log=False):
    lam = _pbc(lam, sample)
    logp = jnp.log(lam) - lam * sample
    return logp if is_log else jnp.exp(logp)


@register(name="_random_pdf_poisson", aliases=("pdf_poisson",))
def pdf_poisson(sample, lam, is_log=False):
    lam = _pbc(lam, sample)
    logp = sample * jnp.log(lam) - lam - jax.scipy.special.gammaln(sample + 1)
    return logp if is_log else jnp.exp(logp)


def _negbin_logpdf(x, limit, prob):
    """lgamma(x+l) - lgamma(x+1) - lgamma(l) + l*log(p) + x*log(1-p); `prob`
    is the failure probability, matching the reference kernel."""
    lg = jax.scipy.special.gammaln
    return (lg(x + limit) - lg(x + 1) - lg(limit)
            + limit * jnp.log(prob) + x * jnp.log(1 - prob))


@register(name="_random_pdf_negative_binomial", aliases=("pdf_negative_binomial",))
def pdf_negative_binomial(sample, k, p, is_log=False):
    logp = _negbin_logpdf(sample, _pbc(k, sample), _pbc(p, sample))
    return logp if is_log else jnp.exp(logp)


@register(name="_random_pdf_generalized_negative_binomial",
          aliases=("pdf_generalized_negative_binomial",))
def pdf_gen_negative_binomial(sample, mu, alpha, is_log=False):
    mu, alpha = _pbc(mu, sample), _pbc(alpha, sample)
    logp = _negbin_logpdf(sample, 1.0 / alpha, 1.0 / (mu * alpha + 1.0))
    return logp if is_log else jnp.exp(logp)


@register(name="_random_pdf_dirichlet", aliases=("pdf_dirichlet",))
def pdf_dirichlet(sample, alpha, is_log=False):
    """alpha: (s..., k); sample: (s..., [m,] k) — density over the last axis."""
    lg = jax.scipy.special.gammaln
    a = alpha[..., None, :] if sample.ndim > alpha.ndim else alpha
    logp = jnp.sum((a - 1) * jnp.log(sample), axis=-1) \
        + lg(jnp.sum(a, axis=-1)) - jnp.sum(lg(a), axis=-1)
    return logp if is_log else jnp.exp(logp)
