"""INT8 quantization operators.

Reference: src/operator/quantization/ — quantize(_v2)/dequantize/
requantize plus quantized_conv/quantized_fully_connected, and the
calibration machinery in calibrate.cc.

TPU-native design: symmetric signed-int8 quantization (the reference's
int8 path); the quantized compute ops consume fp32 tensors plus
calibrated ranges carried as static attrs, quantize on the fly to int8,
run the matmul/conv with int8 inputs accumulating in int32
(`preferred_element_type=int32` — the MXU's native int8 path on real
TPU hardware), and rescale to fp32. This folds the reference's
quantize→compute→requantize→dequantize chains into one fused node per
layer — the XLA-idiomatic shape of the same arithmetic, bit-accurate
int8 compute included."""

import jax
import jax.numpy as jnp

from . import register

INT8_MAX = 127.0


def _scale(min_range, max_range):
    """Symmetric scale: int8 = round(x * 127 / amax)."""
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return INT8_MAX / jnp.maximum(amax, 1e-10)


def _quantize_int8(x, scale):
    q = jnp.clip(jnp.round(x.astype(jnp.float32) * scale),
                 -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8)


@register(name="_contrib_quantize_v2", num_outputs=3,
          differentiable=False)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """fp32 -> (int8, min_range, max_range). Without calib ranges the
    range is the tensor's own min/max (quantize_v2.cc)."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    s = _scale(mn, mx)
    return _quantize_int8(data, s), mn.reshape(1), mx.reshape(1)


@register(name="_contrib_dequantize", differentiable=False)
def dequantize(data, min_range, max_range, out_type="float32"):
    """int8 -> fp32 using the stored range (dequantize.cc)."""
    s = _scale(min_range.reshape(()), max_range.reshape(()))
    return data.astype(jnp.float32) / s


@register(name="_contrib_requantize", num_outputs=3,
          differentiable=False)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator -> int8 with a (possibly calibrated) output
    range (requantize.cc)."""
    in_s = _scale(min_range.reshape(()), max_range.reshape(()))
    real = data.astype(jnp.float32) / in_s
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(real)
        mx = jnp.max(real)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    out_s = _scale(mn, mx)
    return _quantize_int8(real, out_s), mn.reshape(1), mx.reshape(1)


def _int8_matmul(qx, qw):
    """[M,K]i8 x [N,K]i8 -> [M,N]i32 (MXU int8 path)."""
    return jax.lax.dot_general(
        qx, qw, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)


@register(name="_contrib_quantized_fully_connected",
          differentiable=False)
def quantized_fully_connected(data, weight, bias=None, num_hidden=1,
                              no_bias=False, flatten=True,
                              data_min=0.0, data_max=0.0,
                              weight_scale=1.0):
    """FullyConnected in int8: inputs quantized with calibrated
    [data_min, data_max], weight arrives pre-quantized int8 with
    `weight_scale`; fp32 bias is added after rescale
    (quantized_fully_connected.cc)."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    xs = _scale(jnp.float32(data_min), jnp.float32(data_max))
    qx = _quantize_int8(x, xs)
    acc = _int8_matmul(qx, weight)                 # int32
    y = acc.astype(jnp.float32) / (xs * weight_scale)
    if bias is not None and not no_bias:
        y = y + bias
    return y


@register(name="_contrib_quantized_conv", differentiable=False)
def quantized_conv(data, weight, bias=None, kernel=(), stride=(),
                   dilate=(), pad=(), num_filter=1, num_group=1,
                   no_bias=False, layout="NCHW",
                   data_min=0.0, data_max=0.0, weight_scale=1.0):
    """Convolution in int8 with int32 accumulation
    (quantized_conv.cc)."""
    nd_ = len(kernel) if kernel else data.ndim - 2
    stride = tuple(stride) or (1,) * nd_
    dilate = tuple(dilate) or (1,) * nd_
    pad = tuple(pad) or (0,) * nd_
    xs = _scale(jnp.float32(data_min), jnp.float32(data_max))
    qx = _quantize_int8(data, xs)
    spec = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
            3: ("NCDHW", "OIDHW", "NCDHW")}[nd_]
    dn = jax.lax.conv_dimension_numbers(qx.shape, weight.shape, spec)
    acc = jax.lax.conv_general_dilated(
        qx, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) / (xs * weight_scale)
    if bias is not None and not no_bias:
        y = y + bias.reshape((1, -1) + (1,) * nd_)
    return y


def quantize_weight(w):
    """Offline weight quantization: returns (int8 array, scale)."""
    amax = jnp.maximum(jnp.max(jnp.abs(w)), 1e-10)
    s = INT8_MAX / amax
    return _quantize_int8(w, s), float(s)
