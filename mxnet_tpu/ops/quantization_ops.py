"""INT8 quantization operators.

Reference: src/operator/quantization/ — quantize(_v2)/dequantize/
requantize plus quantized_conv/quantized_fully_connected, and the
calibration machinery in calibrate.cc.

TPU-native design: symmetric signed-int8 quantization (the reference's
int8 path); the quantized compute ops consume fp32 tensors plus
calibrated ranges carried as static attrs, quantize on the fly to int8,
run the matmul/conv with int8 inputs accumulating in int32
(`preferred_element_type=int32` — the MXU's native int8 path on real
TPU hardware), and rescale to fp32. This folds the reference's
quantize→compute→requantize→dequantize chains into one fused node per
layer — the XLA-idiomatic shape of the same arithmetic, bit-accurate
int8 compute included."""

import jax
import jax.numpy as jnp

from . import register

INT8_MAX = 127.0


def _scale(min_range, max_range):
    """Symmetric scale: int8 = round(x * 127 / amax)."""
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return INT8_MAX / jnp.maximum(amax, 1e-10)


def _quantize_int8(x, scale):
    q = jnp.clip(jnp.round(x.astype(jnp.float32) * scale),
                 -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8)


@register(name="_contrib_quantize_v2", num_outputs=3,
          differentiable=False)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """fp32 -> (int8, min_range, max_range). Without calib ranges the
    range is the tensor's own min/max (quantize_v2.cc)."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    s = _scale(mn, mx)
    return _quantize_int8(data, s), mn.reshape(1), mx.reshape(1)


@register(name="_contrib_dequantize", differentiable=False)
def dequantize(data, min_range, max_range, out_type="float32"):
    """int8 -> fp32 using the stored range (dequantize.cc)."""
    s = _scale(min_range.reshape(()), max_range.reshape(()))
    return data.astype(jnp.float32) / s


@register(name="_contrib_requantize", num_outputs=3,
          differentiable=False)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator -> int8 with a (possibly calibrated) output
    range (requantize.cc)."""
    # input is INT32: its quantized range is 2^31-1, not 127
    # (requantize.cc MinAbs(MaxValue<SrcDType>(), ...))
    amax = jnp.maximum(jnp.abs(min_range.reshape(())),
                       jnp.abs(max_range.reshape(())))
    in_s = jnp.float32(2 ** 31 - 1) / jnp.maximum(amax, 1e-10)
    real = data.astype(jnp.float32) / in_s
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(real)
        mx = jnp.max(real)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    out_s = _scale(mn, mx)
    return _quantize_int8(real, out_s), mn.reshape(1), mx.reshape(1)


def _int8_matmul(qx, qw):
    """[M,K]i8 x [N,K]i8 -> [M,N]i32 (MXU int8 path)."""
    return jax.lax.dot_general(
        qx, qw, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)


@register(name="_contrib_quantized_fully_connected",
          differentiable=False)
def quantized_fully_connected(data, weight, bias=None, num_hidden=1,
                              no_bias=False, flatten=True,
                              data_min=0.0, data_max=0.0,
                              weight_scale=1.0):
    """FullyConnected in int8: inputs quantized with calibrated
    [data_min, data_max], weight arrives pre-quantized int8 with
    `weight_scale`; fp32 bias is added after rescale
    (quantized_fully_connected.cc)."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    xs = _scale(jnp.float32(data_min), jnp.float32(data_max))
    qx = _quantize_int8(x, xs)
    acc = _int8_matmul(qx, weight)                 # int32
    y = acc.astype(jnp.float32) / (xs * weight_scale)
    if bias is not None and not no_bias:
        y = y + bias
    return y


@register(name="_contrib_quantized_conv", differentiable=False)
def quantized_conv(data, weight, bias=None, kernel=(), stride=(),
                   dilate=(), pad=(), num_filter=1, num_group=1,
                   no_bias=False, layout="NCHW",
                   data_min=0.0, data_max=0.0, weight_scale=1.0):
    """Convolution in int8 with int32 accumulation
    (quantized_conv.cc)."""
    nd_ = len(kernel) if kernel else data.ndim - 2
    stride = tuple(stride) or (1,) * nd_
    dilate = tuple(dilate) or (1,) * nd_
    pad = tuple(pad) or (0,) * nd_
    xs = _scale(jnp.float32(data_min), jnp.float32(data_max))
    qx = _quantize_int8(data, xs)
    spec = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
            3: ("NCDHW", "OIDHW", "NCDHW")}[nd_]
    dn = jax.lax.conv_dimension_numbers(qx.shape, weight.shape, spec)
    acc = jax.lax.conv_general_dilated(
        qx, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) / (xs * weight_scale)
    if bias is not None and not no_bias:
        y = y + bias.reshape((1, -1) + (1,) * nd_)
    return y


def quantize_weight(w):
    """Offline weight quantization: returns (int8 array, scale)."""
    amax = jnp.maximum(jnp.max(jnp.abs(w)), 1e-10)
    s = INT8_MAX / amax
    return _quantize_int8(w, s), float(s)


@register(name="_contrib_quantize", num_outputs=3, differentiable=False)
def quantize(data, min_range, max_range, out_type="int8"):
    """quantize.cc (v1) — the range arrives as two scalar inputs instead
    of static attrs."""
    mn = min_range.reshape(()).astype(jnp.float32)
    mx = max_range.reshape(()).astype(jnp.float32)
    s = _scale(mn, mx)
    return _quantize_int8(data, s), mn.reshape(1), mx.reshape(1)


@register(name="_contrib_quantized_flatten", num_outputs=3,
          differentiable=False)
def quantized_flatten(data, min_data, max_data):
    return (data.reshape(data.shape[0], -1), min_data, max_data)


@register(name="_contrib_quantized_act", num_outputs=3,
          differentiable=False)
def quantized_act(data, min_data, max_data, act_type="relu"):
    """quantized_activation.cc — relu only in the reference int8 path.
    max(0, q) keeps the scale, so the range passes through with the
    negative side clamped."""
    if act_type != "relu":
        raise NotImplementedError(
            "int8 activation supports relu only (as the reference)")
    zero = jnp.zeros((), data.dtype)
    # ranges pass through UNCHANGED: the symmetric scale is set by
    # max(|min|,|max|), so clamping min to 0 would silently rescale the
    # untouched int8 payload
    return (jnp.maximum(data, zero), min_data, max_data)


@register(name="_contrib_quantized_pooling", num_outputs=3,
          differentiable=False)
def quantized_pooling(data, min_data, max_data, kernel=(), pool_type="max",
                      global_pool=False, stride=(), pad=(),
                      pooling_convention="valid", layout="NCHW"):
    """quantized_pooling.cc — max pool stays in int8; avg pool accumulates
    in int32 and divides back, range unchanged."""
    nd_ = len(kernel) if kernel else data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd_
        pad = (0,) * nd_
    stride = tuple(stride) or (1,) * nd_
    pad = tuple(pad) or (0,) * nd_
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pool_type == "max":
        lowest = (jnp.iinfo(data.dtype).min if jnp.issubdtype(data.dtype, jnp.integer)
                  else -jnp.inf)
        out = jax.lax.reduce_window(
            data, jnp.asarray(lowest, data.dtype), jax.lax.max,
            window, strides, pads)
    elif pool_type == "avg":
        wide = data.astype(jnp.int32) if data.dtype == jnp.int8 else data
        acc = jax.lax.reduce_window(
            wide, jnp.asarray(0, wide.dtype), jax.lax.add,
            window, strides, pads)
        denom = 1
        for k in kernel:
            denom *= k
        # lax.div truncates integer quotients toward zero like the
        # reference C++ (// would floor negative sums to one step lower)
        out = (jax.lax.div(acc, jnp.asarray(denom, acc.dtype))
               if acc.dtype == jnp.int32 else acc / denom).astype(data.dtype)
    else:
        raise NotImplementedError("int8 pooling: max/avg only")
    return out, min_data, max_data


@register(name="_contrib_quantized_elemwise_add", num_outputs=3,
          differentiable=False)
def quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    """quantized_elemwise_add.cc — rescale both int8 operands to a common
    real scale, add in int32, emit the widened range."""
    ls = _scale(lhs_min.reshape(()), lhs_max.reshape(()))
    rs = _scale(rhs_min.reshape(()), rhs_max.reshape(()))
    real = lhs.astype(jnp.float32) / ls + rhs.astype(jnp.float32) / rs
    mn = jnp.minimum(lhs_min.reshape(()) + rhs_min.reshape(()), 0.0)
    mx = lhs_max.reshape(()) + rhs_max.reshape(())
    s32 = jnp.float32(2147483647.0) / jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    out = jnp.clip(jnp.round(real * s32), -2147483647.0,
                   2147483647.0).astype(jnp.int32)
    return out, mn.reshape(1), mx.reshape(1)


@register(name="_contrib_quantized_concat", num_outputs=3,
          differentiable=False)
def quantized_concat(*args, dim=1, num_args=None):
    """quantized_concat.cc — inputs [d0..dn, min0, max0, ..]: requantize
    every piece to the widest range, then concat."""
    n = num_args if num_args is not None else len(args) // 3
    datas, mins, maxs = args[:n], args[n::2], args[n + 1::2]
    mins = [m.reshape(()) for m in mins]
    maxs = [m.reshape(()) for m in maxs]
    out_min = mins[0]
    out_max = maxs[0]
    for m in mins[1:]:
        out_min = jnp.minimum(out_min, m)
    for m in maxs[1:]:
        out_max = jnp.maximum(out_max, m)
    out_s = _scale(out_min, out_max)
    pieces = []
    for d, mn, mx in zip(datas, mins, maxs):
        s = _scale(mn, mx)
        pieces.append(_quantize_int8(d.astype(jnp.float32) / s, out_s))
    return (jnp.concatenate(pieces, axis=dim), out_min.reshape(1),
            out_max.reshape(1))


@register(name="_contrib_quantized_batch_norm", num_outputs=3,
          differentiable=False)
def quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                         min_data, max_data, eps=1e-3,
                         min_calib_range=None, max_calib_range=None):
    """quantized_batchnorm.cc — inference BN folded to a per-channel
    scale/shift applied on the dequantized values, requantized to the
    calibrated output range."""
    s = _scale(min_data.reshape(()), max_data.reshape(()))
    x = data.astype(jnp.float32) / s
    inv = gamma / jnp.sqrt(moving_var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    y = x * inv.reshape(shape) + (beta - moving_mean * inv).reshape(shape)
    if min_calib_range is None or max_calib_range is None:
        mn, mx = jnp.min(y), jnp.max(y)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    return _quantize_int8(y, _scale(mn, mx)), mn.reshape(1), mx.reshape(1)


@register(name="_contrib_calibrate_entropy", num_outputs=2,
          differentiable=False)
def calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """calibrate.cc — KL-divergence-optimal threshold from an |x|
    histogram; returns (min_threshold, max_threshold) as scalars. Runs on
    host (np) like the reference: it is a calibration-time op, not part
    of a compiled graph."""
    import numpy as np
    from ..contrib.quantization import _optimal_threshold_kl
    h = np.asarray(hist, dtype=np.float64)
    e = np.asarray(hist_edges, dtype=np.float64)
    thr = _optimal_threshold_kl(h, e, (num_quantized_bins + 1) // 2)
    return (jnp.asarray([-thr], jnp.float32), jnp.asarray([thr], jnp.float32))
