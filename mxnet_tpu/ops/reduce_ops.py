"""Reduction / broadcast-family operators.

Reference: src/operator/tensor/broadcast_reduce_op.h (sum/mean/prod/max/min/
norm/argmax/... with axis/keepdims/exclude semantics shared across the
family). XLA reduces map straight onto these; `exclude=True` inverts the
axis set (reference semantics in broadcast_reduce_op.h ReduceAxesCompute).
"""

import jax.numpy as jnp

from ..base import MXNetError

from . import register


def _norm_axes(axis, ndim, exclude=False):
    if axis is None or axis == ():
        axes = tuple(range(ndim))
    elif isinstance(axis, int):
        axes = (axis % ndim,)
    else:
        axes = tuple(a % ndim for a in axis)
    if exclude:
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _reduce(fn):
    def impl(data, axis=None, keepdims=False, exclude=False):
        axes = _norm_axes(axis, data.ndim, exclude)
        return fn(data, axis=axes, keepdims=keepdims)
    return impl


for _n, _f in [
    ("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
    ("max", jnp.max), ("min", jnp.min),
    ("nansum", jnp.nansum), ("nanprod", jnp.nanprod),
]:
    def _mk(n=_n, f=_f):
        aliases = ("sum_axis",) if n == "sum" else ()
        @register(name=n, aliases=aliases)
        def _op(data, axis=None, keepdims=False, exclude=False):
            return _reduce(f)(data, axis, keepdims, exclude)
    _mk()


@register(name="norm")
def norm(data, ord=2, axis=None, keepdims=False):
    axes = _norm_axes(axis, data.ndim) if axis is not None else None
    if ord == 1:
        r = jnp.sum(jnp.abs(data), axis=axes, keepdims=keepdims)
    else:
        r = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=keepdims))
    return r


def _arg_out_dtype(dim):
    # reference argmax emits float32; beyond int32 range float32 cannot
    # hold the position, so large-tensor mode emits int64 (documented
    # divergence, tests/test_large_tensor.py)
    if dim > 2**31 - 1:
        import jax
        if not jax.config.jax_enable_x64:
            # outside an x64 scope astype('int64') silently lowers to
            # int32, truncating positions beyond 2^31 — fail loudly
            # (large-tensor eager dispatch wraps itself in
            # jax.experimental.enable_x64)
            raise MXNetError(
                "argmax/argmin over an axis longer than 2^31-1 requires "
                "an x64 context inside compiled graphs; wrap the call in "
                "jax.enable_x64(True) or use the eager large-tensor "
                "dispatch")
        return "int64"
    return "float32"


@register(name="argmax", differentiable=False)
def argmax(data, axis=None, keepdims=False):
    if axis is None:
        res = jnp.argmax(data.reshape(-1))
        return res.astype(_arg_out_dtype(data.size))
    r = jnp.argmax(data, axis=axis)
    if keepdims:
        r = jnp.expand_dims(r, axis)
    return r.astype(_arg_out_dtype(data.shape[axis]))


@register(name="argmin", differentiable=False)
def argmin(data, axis=None, keepdims=False):
    if axis is None:
        return jnp.argmin(data.reshape(-1)).astype(
            _arg_out_dtype(data.size))
    r = jnp.argmin(data, axis=axis)
    if keepdims:
        r = jnp.expand_dims(r, axis)
    return r.astype(_arg_out_dtype(data.shape[axis]))


@register(name="argmax_channel", differentiable=False)
def argmax_channel(data):
    """src/operator/tensor/broadcast_reduce_op_index.cc — argmax over axis 1
    on a 2D input (used by Accuracy metric path)."""
    return jnp.argmax(data, axis=-1).astype("float32")


@register(name="pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    """src/operator/tensor/broadcast_reduce_op_index.cc pick."""
    idx = index.astype("int32")
    ax = axis % data.ndim
    if mode == "wrap":
        idx = jnp.mod(idx, data.shape[ax])
    else:
        idx = jnp.clip(idx, 0, data.shape[ax] - 1)
    picked = jnp.take_along_axis(data, jnp.expand_dims(idx, ax), axis=ax)
    if not keepdims:
        picked = jnp.squeeze(picked, axis=ax)
    return picked


@register(name="broadcast_to")
def broadcast_to(data, shape=()):
    # MXNet semantics: 0 in target shape means "keep source dim"
    tgt = tuple(s if s != 0 else data.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(data, tgt)


@register(name="broadcast_like")
def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    tgt = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        tgt[la % lhs.ndim] = rhs.shape[ra % rhs.ndim]
    return jnp.broadcast_to(lhs, tuple(tgt))


@register(name="broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a % data.ndim] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register(name="L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    """src/operator/l2_normalization.cc."""
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise ValueError("unknown mode %s" % mode)
    n = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / n


@register(name="moments", num_outputs=2)
def moments(data, axes=None, keepdims=False):
    """src/operator/nn/moments.cc."""
    ax = _norm_axes(axes, data.ndim) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=keepdims)
    var = jnp.var(data, axis=ax, keepdims=keepdims)
    return mean, var


@register(name="khatri_rao")
def khatri_rao(*args):
    """src/operator/contrib/krprod.cc — column-wise Khatri-Rao product."""
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("ik,jk->ijk", out, m).reshape(-1, out.shape[1])
    return out
