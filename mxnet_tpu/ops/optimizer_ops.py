"""Optimizer update operators.

Reference: src/operator/optimizer_op.cc (sgd/adam/rmsprop/ftrl/ftml/
signsgd/signum/nag + the fused multi-weight and multi-precision
variants) and src/operator/contrib/adamw.cc. These expose the update
math as callable ops (nd.sgd_update(w, g, out=w, ...)) the way the
reference does; the Optimizer classes in optimizer.py use the same
formulas through their own jit-fused helpers.

State semantics follow the reference's FMutateInputs contract: state
inputs (mom/mean/var/...) are updated IN PLACE by the dispatcher
(mutate_inputs), and the op's only declared output is the new weight —
so `nd.sgd_mom_update(w, g, mom, out=w, ...)` leaves both w and mom
advanced, exactly like the reference kernels.
"""

import jax.numpy as jnp

from . import register


def _rescaled(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


# --------------------------------------------------------------- plain --
@register(name="sgd_update", differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _rescaled(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register(name="sgd_mom_update", differentiable=False,
          mutate_inputs=("mom",))
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _rescaled(grad, rescale_grad, clip_gradient)
    mom = momentum * mom - lr * (g + wd * weight)
    return weight + mom, mom


@register(name="nag_mom_update", differentiable=False,
          mutate_inputs=("mom",))
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescaled(grad, rescale_grad, clip_gradient) + wd * weight
    mom = momentum * mom + g
    return weight - lr * (momentum * mom + g), mom


@register(name="signsgd_update", differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _rescaled(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register(name="signum_update", differentiable=False,
          mutate_inputs=("mom",))
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _rescaled(grad, rescale_grad, clip_gradient)
    mom = momentum * mom - (1.0 - momentum) * (g + wd * weight)
    w = weight - lr * jnp.sign(-mom)
    if wd_lh > 0:
        w = w - lr * wd_lh * weight
    return w, mom


@register(name="adam_update", differentiable=False,
          mutate_inputs=("mean", "var"))
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    # reference order (optimizer_op-inl.h AdamUpdate): rescale + wd
    # first, THEN clip the combined term
    g = grad * rescale_grad + wd * weight
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * g
    var = beta2 * var + (1.0 - beta2) * g * g
    return weight - lr * mean / (jnp.sqrt(var) + epsilon), mean, var


def _adamw_math(w32, grad, mean, var, scale, lr, beta1, beta2, epsilon, wd,
                eta, clip_gradient):
    g = grad.astype(jnp.float32) * scale
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * g
    var = beta2 * var + (1.0 - beta2) * g * g
    # decoupled decay is NOT scaled by lr:
    #   w -= eta * (lr * m / (sqrt(v) + eps) + wd * w)    (adamw.cc:73)
    w32 = w32 - eta * (lr * mean / (jnp.sqrt(var) + epsilon) + wd * w32)
    return w32, mean, var


@register(name="_contrib_adamw_update",
          aliases=("_adamw_update", "adamw_update"),
          differentiable=False, mutate_inputs=("mean", "var"))
def adamw_update(weight, grad, mean, var, rescale_grad=None, lr=0.001,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                 clip_gradient=-1.0):
    """contrib/adamw.cc — decoupled weight decay; rescale_grad arrives as
    a tensor (the AMP loss-scale), eta is the schedule multiplier."""
    scale = rescale_grad if rescale_grad is not None else 1.0
    w, mean, var = _adamw_math(weight, grad, mean, var, scale, lr, beta1,
                               beta2, epsilon, wd, eta, clip_gradient)
    return w.astype(weight.dtype), mean, var


@register(name="_contrib_mp_adamw_update", aliases=("_mp_adamw_update",),
          differentiable=False,
          mutate_inputs=("mean", "var", "weight32"))
def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad=None,
                    lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    eta=1.0, clip_gradient=-1.0):
    """contrib/adamw.cc multi-precision variant: the fp32 master copy
    (weight32) carries the update; the low-precision weight output is
    its cast."""
    scale = rescale_grad if rescale_grad is not None else 1.0
    w32, mean, var = _adamw_math(weight32, grad, mean, var, scale, lr,
                                 beta1, beta2, epsilon, wd, eta,
                                 clip_gradient)
    return w32.astype(weight.dtype), mean, var, w32


@register(name="rmsprop_update", differentiable=False,
          mutate_inputs=("n",))
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _rescaled(grad, rescale_grad, clip_gradient) + wd * weight
    n = gamma1 * n + (1.0 - gamma1) * g * g
    w = weight - lr * g / jnp.sqrt(n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n


@register(name="rmspropalex_update", differentiable=False,
          mutate_inputs=("n", "g", "delta"))
def rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    gr = _rescaled(grad, rescale_grad, clip_gradient) + wd * weight
    n = gamma1 * n + (1.0 - gamma1) * gr * gr
    g = gamma1 * g + (1.0 - gamma1) * gr
    delta = gamma2 * delta - lr * gr / jnp.sqrt(n - g * g + epsilon)
    w = weight + delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n, g, delta


@register(name="ftrl_update", differentiable=False,
          mutate_inputs=("z", "n"))
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescaled(grad, rescale_grad, clip_gradient)
    sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
    z = z + g - sigma * weight
    n = n + g * g
    w = jnp.where(
        jnp.abs(z) > lamda1,
        -(z - jnp.sign(z) * lamda1) /
        ((beta + jnp.sqrt(n)) / lr + wd),
        0.0)
    return w, z, n


@register(name="ftml_update", differentiable=False,
          mutate_inputs=("d", "v", "z"))
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0,
                t=1):
    # reference order (FTMLKernel): rescale + wd first, then clip
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    v = beta2 * v + (1.0 - beta2) * g * g
    d_t = (1.0 - beta1 ** t) / lr * \
        (jnp.sqrt(v / (1.0 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    z = beta1 * z + (1.0 - beta1) * g - sigma * weight
    return -z / d_t, d_t, v, z


# --------------------------------------------- fused multi-weight SGD --
def _multi_sgd(arrays, num_weights, lrs, wds, momentum, rescale_grad,
               clip_gradient, has_mom):
    """Shared driver: `arrays` is the reference's interleaved layout
    [w0, g0, (m0,)? w1, g1, (m1,)? ...]."""
    stride = 3 if has_mom else 2
    assert len(arrays) == stride * num_weights, \
        "expected %d arrays for %d weights" % (stride * num_weights,
                                               num_weights)
    new_weights = []
    new_moms = []
    for i in range(num_weights):
        w = arrays[i * stride]
        g = _rescaled(arrays[i * stride + 1], rescale_grad, clip_gradient)
        if has_mom:
            mom = momentum * arrays[i * stride + 2] - \
                lrs[i] * (g + wds[i] * w)
            new_weights.append(w + mom)
            new_moms.append(mom)
        else:
            new_weights.append(w - lrs[i] * (g + wds[i] * w))
    return new_weights + new_moms


def _parse_list(value, n):
    import ast
    if isinstance(value, str):
        value = ast.literal_eval(value)
    if not isinstance(value, (list, tuple)):
        value = (value,) * n
    return [float(v) for v in value]


@register(name="multi_sgd_update", differentiable=False,
          num_outputs="n")
def multi_sgd_update(*arrays, lrs=(0.01,), wds=(0.0,), rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=1):
    return _multi_sgd(list(arrays), num_weights,
                      _parse_list(lrs, num_weights),
                      _parse_list(wds, num_weights),
                      0.0, rescale_grad, clip_gradient, has_mom=False)


def _mom_slots(attrs):
    n = int(attrs.get("num_weights", 1))
    return tuple(3 * i + 2 for i in range(n))


@register(name="multi_sgd_mom_update", differentiable=False,
          num_outputs="n", mutate_inputs=_mom_slots)
def multi_sgd_mom_update(*arrays, lrs=(0.01,), wds=(0.0,), momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=1):
    """Outputs the updated weights; the momentum inputs advance in place
    (FMutateInputs contract, positions resolved from num_weights)."""
    return _multi_sgd(list(arrays), num_weights,
                      _parse_list(lrs, num_weights),
                      _parse_list(wds, num_weights),
                      momentum, rescale_grad, clip_gradient, has_mom=True)


@register(name="preloaded_multi_sgd_update", differentiable=False,
          num_outputs="n")
def preloaded_multi_sgd_update(*arrays, rescale_grad=1.0,
                               clip_gradient=-1.0, num_weights=1):
    """Like multi_sgd_update but lrs/wds arrive as the trailing two
    device arrays (the reference preloads them to avoid host sync)."""
    lrs, wds = arrays[-2], arrays[-1]   # stay on device (traced scalars)
    return _multi_sgd(list(arrays[:-2]), num_weights, lrs, wds, 0.0,
                      rescale_grad, clip_gradient, has_mom=False)


@register(name="preloaded_multi_sgd_mom_update", differentiable=False,
          num_outputs="n", mutate_inputs=_mom_slots)
def preloaded_multi_sgd_mom_update(*arrays, momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=1):
    lrs, wds = arrays[-2], arrays[-1]   # stay on device (traced scalars)
    return _multi_sgd(list(arrays[:-2]), num_weights, lrs, wds, momentum,
                      rescale_grad, clip_gradient, has_mom=True)


# ------------------------------------------------- multi-precision (mp_) --
# Reference: optimizer_op.cc MP_SGD kernels — the master copy `weight32`
# carries the update in fp32; the declared output is the low-precision
# weight cast back down. weight32 (and mom) advance in place.
def _mp_sgd_math(weight, grad, weight32, lr, wd, rescale_grad, clip_gradient):
    g = _rescaled(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register(name="mp_sgd_update", differentiable=False,
          mutate_inputs=("weight32",))
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    return _mp_sgd_math(weight, grad, weight32, lr, wd, rescale_grad,
                        clip_gradient)


@register(name="mp_sgd_mom_update", differentiable=False,
          mutate_inputs=("mom", "weight32"))
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _rescaled(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mom = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + mom
    return w32.astype(weight.dtype), mom, w32


@register(name="mp_nag_mom_update", differentiable=False,
          mutate_inputs=("mom", "weight32"))
def mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescaled(grad.astype(jnp.float32), rescale_grad, clip_gradient) \
        + wd * weight32
    mom = momentum * mom + g
    w32 = weight32 - lr * (momentum * mom + g)
    return w32.astype(weight.dtype), mom, w32


def _mp_w32_slots(attrs):
    n = int(attrs.get("num_weights", 1))
    return tuple(3 * i + 2 for i in range(n))


@register(name="multi_mp_sgd_update", differentiable=False,
          num_outputs="n", mutate_inputs=_mp_w32_slots)
def multi_mp_sgd_update(*arrays, lrs=(0.01,), wds=(0.0,), rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=1):
    """Interleaved [w0, g0, w32_0, w1, g1, w32_1, ...]."""
    lrs = _parse_list(lrs, num_weights)
    wds = _parse_list(wds, num_weights)
    outs, w32s = [], []
    for i in range(num_weights):
        w, g, w32 = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        lo, hi = _mp_sgd_math(w, g, w32, lrs[i], wds[i], rescale_grad,
                              clip_gradient)
        outs.append(lo)
        w32s.append(hi)
    return outs + w32s


def _mp_mom_slots(attrs):
    n = int(attrs.get("num_weights", 1))
    return tuple(4 * i + j for i in range(n) for j in (2, 3))


@register(name="multi_mp_sgd_mom_update", differentiable=False,
          num_outputs="n", mutate_inputs=_mp_mom_slots)
def multi_mp_sgd_mom_update(*arrays, lrs=(0.01,), wds=(0.0,), momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=1):
    """Interleaved [w0, g0, mom0, w32_0, ...]; mom and w32 advance in place."""
    lrs = _parse_list(lrs, num_weights)
    wds = _parse_list(wds, num_weights)
    outs, states = [], []
    for i in range(num_weights):
        w, g, mom, w32 = arrays[4 * i:4 * i + 4]
        gg = _rescaled(g.astype(jnp.float32), rescale_grad, clip_gradient)
        mom = momentum * mom - lrs[i] * (gg + wds[i] * w32)
        w32 = w32 + mom
        outs.append(w32.astype(w.dtype))
        states.extend([mom, w32])
    return outs + states


# ------------------------------------------------------------- adagrad --
@register(name="_sparse_adagrad_update", differentiable=False,
          mutate_inputs=("history",))
def sparse_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """optimizer_op.cc:893 — history += g^2; w -= lr * g / sqrt(history+eps).
    The reference's row_sparse laziness (update only rows present in the
    gradient) is a dense no-op here: a dense grad touches every row."""
    if wd:
        # match the reference's fail-fast (optimizer_op-inl.h:2206) instead
        # of silently training without decay
        raise ValueError("sparse adagrad_update does not support wd.")
    g = _rescaled(grad, rescale_grad, clip_gradient)
    history = history + g * g
    return weight - lr * g / jnp.sqrt(history + epsilon), history


@register(name="_contrib_group_adagrad_update", differentiable=False,
          mutate_inputs=("history",))
def group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """contrib/optimizer_op.cc — one accumulator per row: history_r +=
    mean_j(g_rj^2); w_rj -= lr * g_rj / sqrt(history_r + eps)."""
    g = _rescaled(grad, rescale_grad, clip_gradient)
    row = g.reshape(g.shape[0], -1)
    history = history + jnp.mean(row * row, axis=1).reshape(history.shape)
    denom = jnp.sqrt(history + epsilon).reshape(
        (g.shape[0],) + (1,) * (g.ndim - 1))
    return weight - lr * g / denom, history
