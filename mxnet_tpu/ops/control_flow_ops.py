"""Control-flow operators: _foreach, _while_loop, _cond.

Reference: src/operator/control_flow.cc (_foreach :1089, _while_loop
:1150, _cond :1211) — stateful subgraph ops executed by the legacy engine
node-by-node per iteration.

TPU-native design: the subgraph (a Symbol) is a static attr of the node;
lowering turns it into a pure jax function (executor.build_graph_fn) and
wraps it in the native XLA structured-control-flow primitive:

  _foreach     -> lax.scan        (differentiable, one compiled body)
  _while_loop  -> lax.scan over max_iterations steps with an active mask
                  (lax.while_loop is not reverse-mode differentiable and
                  dynamic trip counts defeat XLA static shapes; the
                  masked scan is differentiable and TPU-friendly, at the
                  cost of always running max_iterations steps — the
                  reference also fixes the output's leading dim to
                  max_iterations for the same shape-inference reason)
  _cond        -> lax.cond        (single branch executed, differentiable)

Subgraph free variables (closure captures) are explicit trailing inputs
of the node, so gradients flow to them like any other input.
"""

import jax
import jax.numpy as jnp

from . import register


def _graph_fn(subgraph, is_train):
    from ..executor import build_graph_fn
    return build_graph_fn(subgraph, is_train)


def _key(rng_key):
    return rng_key if rng_key is not None else jax.random.PRNGKey(0)


@register(name="_foreach", num_outputs="n", stateful_rng=True)
def _foreach(*arrays, subgraph=None, sub_in_names=(), num_data=1,
             num_out_data=0, num_states=0, is_train=False, rng_key=None):
    """Scan `subgraph` over axis 0 of the data inputs.

    Inputs: num_data data arrays, num_states carry states, then free
    (closure) arrays. Subgraph outputs: num_out_data per-step outputs
    followed by num_states new states. Returns stacked outputs + final
    states."""
    data = arrays[:num_data]
    states = tuple(arrays[num_data:num_data + num_states])
    free = arrays[num_data + num_states:]
    names = list(sub_in_names)
    data_names = names[:num_data]
    state_names = names[num_data:num_data + num_states]
    free_names = names[num_data + num_states:]
    gfn = _graph_fn(subgraph, is_train)
    key = _key(rng_key)

    def step(carry, xs_and_key):
        xs, k = xs_and_key
        args = dict(zip(data_names, xs))
        args.update(zip(state_names, carry))
        args.update(zip(free_names, free))
        outs, _ = gfn(args, {}, k)
        return tuple(outs[num_out_data:]), tuple(outs[:num_out_data])

    n_steps = data[0].shape[0]
    keys = jax.random.split(key, n_steps)
    final_states, stacked = jax.lax.scan(step, states, (tuple(data), keys))
    return tuple(stacked) + tuple(final_states)


@register(name="_while_loop", num_outputs="n", stateful_rng=True)
def _while_loop(*arrays, cond_graph=None, func_graph=None, sub_in_names=(),
                num_out_data=0, num_vars=0, max_iterations=None,
                is_train=False, rng_key=None):
    """Masked-scan while loop: runs max_iterations steps; once the cond
    subgraph reports false, loop vars freeze and step outputs stop being
    written (rows beyond the trip count stay zero — the reference leaves
    them undefined)."""
    assert max_iterations is not None and max_iterations > 0, \
        "while_loop requires a positive max_iterations"
    loop_vars = tuple(arrays[:num_vars])
    free = arrays[num_vars:]
    names = list(sub_in_names)
    var_names = names[:num_vars]
    free_names = names[num_vars:]
    cfn = _graph_fn(cond_graph, is_train)
    ffn = _graph_fn(func_graph, is_train)
    key = _key(rng_key)

    def step(carry, k):
        vars_, active = carry
        args = dict(zip(var_names, vars_))
        args.update(zip(free_names, free))
        (pred,), _ = cfn(args, {}, k)
        active = jnp.logical_and(active,
                                 jnp.reshape(pred, ()).astype(bool))
        outs, _ = ffn(args, {}, k)
        step_outs = outs[:num_out_data]
        new_vars = outs[num_out_data:]
        sel_vars = tuple(jnp.where(active, nv, ov)
                         for nv, ov in zip(new_vars, vars_))
        emitted = tuple(jnp.where(active, so, jnp.zeros_like(so))
                        for so in step_outs)
        return (sel_vars, active), emitted

    keys = jax.random.split(key, int(max_iterations))
    (final_vars, _), stacked = jax.lax.scan(
        step, (loop_vars, jnp.asarray(True)), keys)
    return tuple(stacked) + tuple(final_vars)


@register(name="_cond", num_outputs="n", stateful_rng=True)
def _cond(*arrays, then_graph=None, else_graph=None, sub_in_names=(),
          num_outputs_branch=0, is_train=False, rng_key=None):
    """lax.cond over the two branch subgraphs. Input 0 is the scalar
    predicate; the rest are the union of both branches' free inputs."""
    pred = jnp.reshape(arrays[0], ()).astype(bool)
    free = arrays[1:]
    names = list(sub_in_names)
    tfn = _graph_fn(then_graph, is_train)
    efn = _graph_fn(else_graph, is_train)
    key = _key(rng_key)
    args = dict(zip(names, free))

    def then_branch(_):
        outs, _aux = tfn(args, {}, key)
        return tuple(outs)

    def else_branch(_):
        outs, _aux = efn(args, {}, key)
        return tuple(outs)

    return jax.lax.cond(pred, then_branch, else_branch, None)
