"""Linear-algebra operators (`linalg_*`).

Reference: src/operator/tensor/la_op.cc — gemm/gemm2/potrf/potri/trsm/trmm/
sumlogdiag/syrk/gelqf/syevd/inverse/det/slogdet over LAPACK/cuBLAS. Here:
jnp.linalg / lax.linalg, which XLA lowers to MXU matmuls + host LAPACK
where needed.
"""

import jax.numpy as jnp
from jax import lax

from . import register


@register(name="linalg_gemm", aliases=("_linalg_gemm",))
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register(name="linalg_gemm2", aliases=("_linalg_gemm2",))
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register(name="linalg_potrf", aliases=("_linalg_potrf",))
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register(name="linalg_potri", aliases=("_linalg_potri",))
def linalg_potri(A):
    # A is the cholesky factor L; potri returns (L L^T)^-1
    eye = jnp.eye(A.shape[-1], dtype=A.dtype)
    Linv = lax.linalg.triangular_solve(A, jnp.broadcast_to(eye, A.shape),
                                       lower=True, left_side=True)
    return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)


@register(name="linalg_trsm", aliases=("_linalg_trsm",))
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    return lax.linalg.triangular_solve(
        A, alpha * B, left_side=not rightside, lower=lower,
        transpose_a=transpose)


@register(name="linalg_trmm", aliases=("_linalg_trmm",))
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B))


@register(name="linalg_sumlogdiag", aliases=("_linalg_sumlogdiag",))
def linalg_sumlogdiag(A):
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register(name="linalg_extractdiag", aliases=("_linalg_extractdiag",))
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register(name="linalg_makediag", aliases=("_linalg_makediag",))
def linalg_makediag(A, offset=0):
    n = A.shape[-1] + abs(offset)
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    idx = jnp.arange(A.shape[-1])
    if offset >= 0:
        return out.at[..., idx, idx + offset].set(A)
    return out.at[..., idx - offset, idx].set(A)


@register(name="linalg_extracttrian", aliases=("_linalg_extracttrian",))
def linalg_extracttrian(A, offset=0, lower=True):
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower else jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


@register(name="linalg_syrk", aliases=("_linalg_syrk",))
def linalg_syrk(A, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


@register(name="linalg_gelqf", aliases=("_linalg_gelqf",), num_outputs=2)
def linalg_gelqf(A):
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register(name="linalg_syevd", aliases=("_linalg_syevd",), num_outputs=2)
def linalg_syevd(A):
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register(name="linalg_inverse", aliases=("inverse", "_linalg_inverse"))
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register(name="linalg_det", aliases=("det", "_linalg_det"))
def linalg_det(A):
    return jnp.linalg.det(A)


@register(name="linalg_slogdet", aliases=("slogdet", "_linalg_slogdet"), num_outputs=2)
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


@register(name="linalg_maketrian", aliases=("_linalg_maketrian",))
def linalg_maketrian(A, offset=0, lower=True):
    # inverse of extracttrian for square output
    m = A.shape[-1]
    n = int((jnp.sqrt(8 * m + 1) - 1) / 2) if offset == 0 else None
    import math
    n = int((math.isqrt(8 * m + 1) - 1) // 2)
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    import numpy as _np
    rows, cols = (_np.tril_indices(n, k=offset) if lower
                  else _np.triu_indices(n, k=offset))
    return out.at[..., rows, cols].set(A)
