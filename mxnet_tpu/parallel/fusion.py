"""Bucketed gradient fusion — the DDP-class comm optimization.

The reference amortizes per-key communication three ways: comm.h groups
keys before reducing, MXNET_KVSTORE_BIGARRAY_BOUND shards big arrays,
and engine priorities overlap comm with remaining backward compute
(SURVEY §2.3). On this stack every per-key push is one XLA collective
dispatch, so a ResNet/LM-sized model pays hundreds of small dispatches
per step — exactly the per-key tax this module removes:

* ``plan_buckets`` packs keys, in the caller's (priority) order, into
  fixed-byte buckets (``MXNET_KVSTORE_BUCKET_BYTES``, default 25 MB —
  the same knob class as the reference's bigarray bound). Segments of
  different dtypes never share a flat buffer (bit-exactness first), so
  a bucket holds one *lane* per dtype.
* ``pack_lane`` / ``unpack_lane`` are pure jnp (trace-friendly) flatten/
  concat/slice helpers shared by the eager KVStore path and the in-jit
  path.
* ``bucketed_all_reduce`` is the in-jit form: inside shard_map/pjit it
  emits ONE psum per bucket lane, which XLA schedules asynchronously —
  collectives for already-finished buckets overlap the remaining
  backward compute (the reference's priority overlap, expressed in the
  graph as "Automatic Cross-Replica Sharding of Weight Update ..."
  (PAPERS.md) and the TF design argue it should be).
* ``FlatOptimizer`` + ``ShardSlot`` implement the cross-replica-sharded
  weight update (``MXNET_KVSTORE_SHARD_UPDATE=1``): per bucket lane,
  reduce-scatter the flat gradient, update a 1/N shard of the flat
  master weight + optimizer state per device, all-gather the updated
  weight. Optimizer FLOPs and master/optimizer state bytes per replica
  drop by (N-1)/N (the PAPERS.md win).
"""

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                    # jax >= 0.5 top-level alias
    _shard_map = jax.shard_map
except AttributeError:                  # 0.4.x experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

from .. import _fastenv
from ..observability import chaos as _chaos
from ..observability import watchdog as _wd

__all__ = ["DEFAULT_BUCKET_BYTES", "bucket_bytes", "fusion_enabled",
           "shard_update_enabled", "Segment", "Lane", "Bucket",
           "plan_buckets", "plan_signature", "pack_lane", "unpack_lane",
           "bucketed_all_reduce", "FlatOptimizer", "ShardSlot"]

DEFAULT_BUCKET_BYTES = 25 << 20          # ~25 MB, torch-DDP-class default


def bucket_bytes(override=None):
    """Bucket byte budget: explicit arg > env knob > 25 MB default."""
    if override is not None:
        return int(override)
    return int(_fastenv.get("MXNET_KVSTORE_BUCKET_BYTES",
                            DEFAULT_BUCKET_BYTES))


def fusion_enabled():
    """MXNET_KVSTORE_FUSION gates the bucketed Trainer/Module paths
    (default ON; =0 restores per-key push/pull)."""
    return _fastenv.get("MXNET_KVSTORE_FUSION", "1").lower() \
        not in ("0", "false")


def shard_update_enabled():
    """MXNET_KVSTORE_SHARD_UPDATE=1 lowers each bucket to
    reduce-scatter -> sharded optimizer update -> all-gather."""
    return _fastenv.get("MXNET_KVSTORE_SHARD_UPDATE", "0").lower() \
        in ("1", "true")


DEFAULT_BIGARRAY_BOUND = 1_000_000       # elements — the reference default


def bigarray_bound():
    """MXNET_KVSTORE_BIGARRAY_BOUND (elements, reference kvstore_dist.h
    default 1e6): arrays above the bound travel ALONE. A single-segment
    lane packs as a reshape view — no concat copy — so big tensors pay
    zero packing overhead while the small-tensor tail still fuses."""
    return int(_fastenv.get("MXNET_KVSTORE_BIGARRAY_BOUND",
                            DEFAULT_BIGARRAY_BOUND))


# ------------------------------------------------------------ planning --

class Segment(object):
    """One key's slice of a lane's flat buffer."""
    __slots__ = ("key", "shape", "dtype", "size", "offset")

    def __init__(self, key, shape, dtype, size, offset):
        self.key, self.shape, self.dtype = key, tuple(shape), dtype
        self.size, self.offset = size, offset

    def __repr__(self):
        return "Segment(%r, %s, %s, @%d)" % (self.key, self.shape,
                                             self.dtype, self.offset)


class Lane(object):
    """All same-dtype segments of one bucket, flattened back to back.
    Mixed dtypes never share a buffer: concatenating them would force a
    cast and break bit-exactness with the per-key path."""
    __slots__ = ("dtype", "segments", "size")

    def __init__(self, dtype):
        self.dtype = dtype
        self.segments = []
        self.size = 0

    @property
    def nbytes(self):
        return self.size * np.dtype(self.dtype).itemsize


class Bucket(object):
    __slots__ = ("index", "lanes", "nbytes")

    def __init__(self, index):
        self.index = index
        self.lanes = []                  # ordered by first appearance
        self.nbytes = 0

    def _lane(self, dtype):
        for lane in self.lanes:
            if lane.dtype == dtype:
                return lane
        lane = Lane(dtype)
        self.lanes.append(lane)
        return lane

    def add(self, key, shape, dtype):
        lane = self._lane(dtype)
        size = int(np.prod(shape)) if len(shape) else 1
        lane.segments.append(Segment(key, shape, dtype, size, lane.size))
        lane.size += size
        self.nbytes += size * np.dtype(dtype).itemsize


def plan_buckets(entries, max_bytes=None):
    """Greedy fixed-byte bucketing in the given (priority) order.

    entries: iterable of (key, shape, dtype). A bucket closes when the
    next entry would push it past the byte budget. Arrays above
    MXNET_KVSTORE_BIGARRAY_BOUND elements travel ALONE (the reference's
    bigarray rule, kvstore_dist.h): a single-segment lane flattens as a
    reshape view instead of a concat copy, so big tensors pay no
    packing overhead while the small-tensor tail still fuses. Callers
    pass entries in reverse-registration order so the bucket holding
    the LAST layers' gradients — ready first in backward — reduces
    first.
    """
    max_bytes = bucket_bytes(max_bytes)
    solo_elems = bigarray_bound()
    buckets = []
    cur = None
    for key, shape, dtype in entries:
        dtype = str(np.dtype(dtype))
        size = int(np.prod(shape)) if len(shape) else 1
        nbytes = size * np.dtype(dtype).itemsize
        if size > solo_elems:
            solo = Bucket(len(buckets))
            buckets.append(solo)
            solo.add(key, shape, dtype)
            cur = None                   # never append after a bigarray
            continue
        if cur is None or (cur.nbytes and cur.nbytes + nbytes > max_bytes):
            cur = Bucket(len(buckets))
            buckets.append(cur)
        cur.add(key, shape, dtype)
    return buckets


def plan_signature(entries, max_bytes=None):
    """Hashable identity of a plan — kvstore caches plans per signature."""
    return (bucket_bytes(max_bytes), bigarray_bound(),
            tuple((k, tuple(s), str(np.dtype(d))) for k, s, d in entries))


# ------------------------------------------------------- pack / unpack --

def pack_lane(lane, values, pad_to=None):
    """Concat one worker's arrays for this lane into a flat buffer.
    ``values``: key -> array. Pure jnp — usable eagerly and under jit.
    ``pad_to`` zero-pads the tail (shard paths need length % n == 0)."""
    flats = [jnp.ravel(values[seg.key]) for seg in lane.segments]
    flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
    if pad_to is not None and pad_to > lane.size:
        flat = jnp.concatenate(
            [flat, jnp.zeros(pad_to - lane.size, dtype=flat.dtype)])
    return flat


def unpack_lane(flat, lane):
    """Inverse of pack_lane: flat buffer -> {key: array} views. A
    single-segment (bigarray) lane is just a reshape — no slice op."""
    if len(lane.segments) == 1 and lane.segments[0].size == flat.shape[0]:
        seg = lane.segments[0]
        return {seg.key: flat.reshape(seg.shape)}
    return {seg.key: jax.lax.slice_in_dim(
        flat, seg.offset, seg.offset + seg.size).reshape(seg.shape)
        for seg in lane.segments}


# ------------------------------------------------------- in-jit fusion --

def bucketed_all_reduce(values, axis_name="dp", max_bytes=None,
                        keys=None):
    """Fused all-reduce for use INSIDE shard_map/pjit.

    ``values``: list of (traced) arrays, already in priority order.
    Emits one ``lax.psum`` per bucket lane instead of one per array, so
    a jitted train step dispatches O(total_bytes / bucket_bytes)
    collectives; XLA overlaps each bucket's psum with whatever backward
    compute has not produced the next bucket yet. Returns the reduced
    arrays in input order.
    """
    keys = list(range(len(values))) if keys is None else list(keys)
    by_key = dict(zip(keys, values))
    plan = plan_buckets(
        [(k, by_key[k].shape, by_key[k].dtype) for k in keys], max_bytes)
    out = {}
    for bucket in plan:
        for lane in bucket.lanes:
            flat = pack_lane(lane, by_key)
            red = jax.lax.psum(flat, axis_name)
            out.update(unpack_lane(red, lane))
    return [out[k] for k in keys]


# ---------------------------------------------- sharded weight update --

class FlatOptimizer(object):
    """Flat elementwise form of an Optimizer's update rule.

    The sharded update applies the optimizer to a 1/N shard of a flat
    bucket, so the rule must be elementwise over the flat buffer with
    scalar (or per-element) hyperparameters. Supported rules mirror the
    jitted kernels in optimizer.py exactly (same math, same order of
    operations): sgd (+momentum), nag, adam. ``supports`` returns None
    for anything else and callers fall back to the replicated per-key
    update.
    """

    RULES = {
        "sgd": 1, "nag": 1, "adam": 2,          # name -> n state buffers
    }

    def __init__(self, optimizer, name):
        self.optimizer = optimizer
        self.name = name
        self.n_states = 0 if name in ("sgd", "nag") \
            and not getattr(optimizer, "momentum", 0.0) \
            else self.RULES[name]

    @classmethod
    def supports(cls, optimizer):
        """A FlatOptimizer when the rule is shardable, else None.
        Subclass instances are rejected: an override of update()/
        _apply_rule would silently diverge from the flat rule."""
        if optimizer is None:
            return None
        for name, klass in (("sgd", "SGD"), ("nag", "NAG"),
                            ("adam", "Adam")):
            mod = type(optimizer).__module__
            if type(optimizer).__name__ == klass \
                    and mod.endswith("optimizer"):
                return cls(optimizer, name)
        return None

    # hyperparameters resolved host-side per step (cheap scalars); the
    # compiled shard function takes them as traced operands so schedules
    # never recompile
    def step_scalars(self, t):
        o = self.optimizer
        lr = o.learning_rate
        if self.name == "adam":
            lr = lr * math.sqrt(1.0 - o.beta2 ** t) / (1.0 - o.beta1 ** t)
        return (np.float32(lr), np.float32(o.wd),
                np.float32(o.rescale_grad))

    def extra_scalars(self):
        o = self.optimizer
        if self.name == "adam":
            return (np.float32(o.beta1), np.float32(o.beta2),
                    np.float32(o.epsilon))
        return (np.float32(getattr(o, "momentum", 0.0)),)

    @property
    def clip(self):
        c = self.optimizer.clip_gradient
        return None if c is None else float(c)

    def apply(self, w, g, states, lr, wd, extra, clip, lr_mult=None,
              wd_mult=None):
        """The elementwise rule — called inside the compiled shard map.
        Matches optimizer.py's _sgd_update/_sgd_mom_update/
        _nag_mom_update/_adam_update bit for bit on each element."""
        if clip is not None:
            g = jnp.clip(g, -clip, clip)
        if lr_mult is not None:
            lr = lr * lr_mult
        if wd_mult is not None:
            wd = wd * wd_mult
        if self.name == "adam":
            beta1, beta2, eps = extra
            m, v = states
            g = g + wd * w
            m = beta1 * m + (1.0 - beta1) * g
            v = beta2 * v + (1.0 - beta2) * g * g
            return w - lr * m / (jnp.sqrt(v) + eps), (m, v)
        (momentum,) = extra
        if not self.n_states:
            return w - lr * (g + wd * w), ()
        (mom,) = states
        if self.name == "nag":
            g = g + wd * w
            mom = momentum * mom + g
            return w - lr * (momentum * mom + g), (mom,)
        mom = momentum * mom - lr * (g + wd * w)
        return w + mom, (mom,)


@functools.lru_cache(maxsize=256)
def _shard_update_fn(devices, n, l_pad, wdtype, gdtype, rule_name,
                     n_states, has_clip, has_mults, scatter):
    """Compiled reduce-scatter -> shard update -> (sharded out) program
    for one bucket lane. Cached per lane geometry; hyperparameters ride
    as traced scalars.

    ``scatter=True`` takes [n, l_pad] per-worker gradients and
    reduce-scatters them (the multi-worker push path). ``scatter=False``
    takes one already-reduced flat gradient laid out P('worker') — each
    device just updates its slice (the Trainer path, where XLA reduced
    the grad inside the step already)."""
    mesh = Mesh(np.asarray(devices), ("worker",))
    g_spec = P("worker", None) if scatter else P("worker")
    s_spec = P("worker")                     # flat shards [l_pad/n]
    r_spec = P()                             # replicated scalars

    def local(g, w, states, scalars, mults):
        lr, wd, rescale, clip, extra = scalars
        if scatter:
            g = jax.lax.psum_scatter(g.reshape(-1), "worker",
                                     scatter_dimension=0, tiled=True)
        g = g.astype(w.dtype) * rescale
        lr_mult, wd_mult = mults if has_mults else (None, None)
        rule = _RULE_CACHE[(rule_name, n_states)]
        w, states = rule(w, g, states, lr, wd, extra,
                         clip if has_clip else None, lr_mult, wd_mult)
        return w, states

    in_specs = (g_spec, s_spec, tuple(s_spec for _ in range(n_states)),
                (r_spec, r_spec, r_spec, r_spec,
                 tuple(r_spec for _ in range(_N_EXTRA[rule_name]))),
                (s_spec, s_spec) if has_mults else (r_spec, r_spec))
    out_specs = (s_spec, tuple(s_spec for _ in range(n_states)))
    mapped = _shard_map(local, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs)
    return jax.jit(mapped, donate_argnums=(1, 2))


# rule fns used inside the compiled program; mirrors FlatOptimizer.apply
_N_EXTRA = {"sgd": 1, "nag": 1, "adam": 3}


def _make_rule(name, n_states):
    def rule(w, g, states, lr, wd, extra, clip, lr_mult, wd_mult):
        shim = FlatOptimizer.__new__(FlatOptimizer)
        shim.name = name
        shim.n_states = n_states
        return shim.apply(w, g, states, lr, wd, extra, clip,
                          lr_mult, wd_mult)
    return rule


_RULE_CACHE = {}


@functools.lru_cache(maxsize=64)
def _gather_fn(devices, l_pad, dtype):
    """All-gather a sharded flat buffer back to replicated (the third
    leg of reduce-scatter -> update -> all-gather)."""
    mesh = Mesh(np.asarray(devices), ("worker",))
    return jax.jit(lambda x: x,
                   out_shardings=NamedSharding(mesh, P()))


class ShardSlot(object):
    """Persistent sharded state for one bucket lane: flat master weight
    plus optimizer state, each a [l_pad] global array sharded 1/N per
    device over the worker axis. Per-replica bytes for master+state are
    total/N — the (N-1)/N cut of "Automatic Cross-Replica Sharding of
    Weight Update in Data-Parallel Training" (PAPERS.md).
    """

    def __init__(self, lane, devices, weights, flat_opt, t0=0):
        self.lane = lane
        self.devices = tuple(devices)
        self.n = len(self.devices)
        self.l_pad = -(-lane.size // self.n) * self.n   # ceil to n
        self.flat_opt = flat_opt
        self.t = int(t0)
        mesh = Mesh(np.asarray(self.devices), ("worker",))
        self._mesh = mesh
        self._shard = NamedSharding(mesh, P("worker"))
        self._g_shard = NamedSharding(mesh, P("worker", None))
        # master weight: fp32 when the optimizer runs multi-precision on
        # a low-precision lane (the fp32-master-state the paper shards)
        wdtype = np.dtype(lane.dtype)
        self.master_fp32 = bool(
            getattr(flat_opt.optimizer, "multi_precision", False)
            and wdtype == np.dtype(jnp.bfloat16))
        mdtype = np.dtype(np.float32) if self.master_fp32 else wdtype
        self.mdtype = mdtype
        flat_w = pack_lane(lane, weights, pad_to=self.l_pad)
        self.flat_w = jax.device_put(flat_w.astype(mdtype), self._shard)
        self.states = tuple(
            jax.device_put(jnp.zeros(self.l_pad, mdtype), self._shard)
            for _ in range(flat_opt.n_states))
        self._mults = self._build_mults()
        rule_name = flat_opt.name
        rule_key = (rule_name, flat_opt.n_states)
        if rule_key not in _RULE_CACHE:
            _RULE_CACHE[rule_key] = _make_rule(rule_name,
                                               flat_opt.n_states)
        self._fns = {
            scatter: _shard_update_fn(
                self.devices, self.n, self.l_pad, str(mdtype),
                str(lane.dtype), rule_name, flat_opt.n_states,
                flat_opt.clip is not None, self._mults is not None,
                scatter)
            for scatter in (True, False)}

    def _build_mults(self):
        """Per-element lr/wd multiplier vectors — only materialized when
        some segment's multiplier differs from 1 (Module set_lr_mult /
        set_wd_mult tables); the common case stays scalar."""
        o = self.flat_opt.optimizer
        idxs = [int(s.key) if str(s.key).isdigit() else s.key
                for s in self.lane.segments]
        try:
            lrs = [o._get_lr(i) for i in idxs]
            wds = [o._get_wd(i) for i in idxs]
        except Exception:
            return None
        base_lr = o.learning_rate or 1.0
        lr_r = [l / base_lr if base_lr else 1.0 for l in lrs]
        wd_r = [w / o.wd if o.wd else 1.0 for w in wds]
        if all(abs(r - 1.0) < 1e-12 for r in lr_r + wd_r):
            return None
        lr_vec = np.ones(self.l_pad, np.float32)
        wd_vec = np.ones(self.l_pad, np.float32)
        for seg, lm, wm in zip(self.lane.segments, lr_r, wd_r):
            lr_vec[seg.offset:seg.offset + seg.size] = lm
            wd_vec[seg.offset:seg.offset + seg.size] = wm
        return (jax.device_put(jnp.asarray(lr_vec), self._shard),
                jax.device_put(jnp.asarray(wd_vec), self._shard))

    @property
    def state_bytes_total(self):
        per = self.l_pad * self.mdtype.itemsize
        return per * (len(self.states) + (1 if self.master_fp32 else 0))

    @property
    def state_bytes_per_replica(self):
        return self.state_bytes_total // self.n

    def step(self, per_worker_flats):
        """One sharded update from per-worker flat gradient buffers
        (each already padded to l_pad). With exactly n buffers the
        reduction is a reduce-scatter; with one (the Trainer path — XLA
        already reduced the grad) or a mismatched count, the summed
        flat gradient is sliced across devices instead. Returns the
        updated flat weight REPLICATED (the all-gather leg), in the
        lane dtype."""
        self.t += 1
        scatter = len(per_worker_flats) == self.n and self.n > 1
        if scatter:
            shards = [jax.device_put(f[None], d)
                      for f, d in zip(per_worker_flats, self.devices)]
            g = jax.make_array_from_single_device_arrays(
                (self.n, self.l_pad), self._g_shard, shards)
        else:
            g = per_worker_flats[0]
            for f in per_worker_flats[1:]:
                g = g + f
            g = jax.device_put(g, self._shard)
        lr, wd, rescale = self.flat_opt.step_scalars(self.t)
        clip = self.flat_opt.clip
        scalars = (jnp.float32(lr), jnp.float32(wd),
                   jnp.float32(rescale),
                   jnp.float32(0.0 if clip is None else clip),
                   tuple(jnp.float32(x)
                         for x in self.flat_opt.extra_scalars()))
        mults = self._mults if self._mults is not None \
            else (jnp.float32(1.0), jnp.float32(1.0))
        # reduce-scatter -> update -> all-gather is two collective
        # dispatches; a post-mortem should name the lane that hung
        with _wd.watch("fusion.shard_update", lane=str(self.lane.dtype),
                       bytes=self.l_pad * self.mdtype.itemsize,
                       keys=len(self.lane.segments)):
            if _chaos.enabled():
                # chaos site: the sharded-update program is one of the
                # named collectives the injection harness can hang
                _chaos.fire("fusion.shard_update",
                            lane=str(self.lane.dtype))
            self.flat_w, self.states = self._fns[scatter](
                g, self.flat_w, self.states, scalars, mults)
            gathered = _gather_fn(self.devices, self.l_pad,
                                  str(self.mdtype))(self.flat_w)
        if self.master_fp32:
            gathered = gathered.astype(np.dtype(self.lane.dtype))
        return gathered

    # ------------------------------------------------- state (de)hydrate --
    def get_state(self):
        """Host snapshot for save_optimizer_states round-trips."""
        return {"t": self.t,
                "flat_w": np.asarray(self.flat_w),
                "states": [np.asarray(s) for s in self.states]}

    def set_state(self, snap):
        self.t = int(snap["t"])
        self.flat_w = jax.device_put(
            jnp.asarray(snap["flat_w"], self.mdtype), self._shard)
        self.states = tuple(
            jax.device_put(jnp.asarray(s, self.mdtype), self._shard)
            for s in snap["states"])
