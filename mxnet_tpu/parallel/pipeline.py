"""Pipeline parallelism — stage-major layer stacking + collective-permute
microbatch schedule over the 'pp' mesh axis.

Capability extension over the reference (SURVEY §2.3 "NOT PRESENT": MXNet
1.x has only DP + manual-placement MP). TPU-native design: the L layers
of a homogeneous stack are grouped into S = |pp| stages; each device
holds its stage's L/S layer parameters (leading dim sharded over pp).
Microbatches enter stage 0 one per tick; activations rotate to the next
stage with lax.ppermute, so after the S-1-tick fill bubble every device
computes every tick (the GPipe schedule on an ICI ring). Everything is
lax.scan + ppermute: differentiable, one compiled program, no host
round-trips.

The whole schedule runs inside one jax.shard_map that is *manual* over
pp (and optionally other axes the caller's layer_fn needs, e.g. 'sp' for
ring attention inside a stage); the remaining mesh axes stay auto, so
tp/ep sharding of the layer weights continues to be GSPMD's job.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import ring_permute

__all__ = ["stack_stage_params", "spmd_pipeline"]


def stack_stage_params(layer_params, n_stages):
    """List of L per-layer pytrees -> one pytree with leading dims
    [S, L/S] (stage-major), ready to shard P('pp', ...)."""
    L = len(layer_params)
    if L % n_stages != 0:
        raise ValueError("n_layers (%d) must divide by n_stages (%d)"
                         % (L, n_stages))
    per = L // n_stages
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layer_params)
    return jax.tree.map(
        lambda x: x.reshape((n_stages, per) + x.shape[1:]), stacked)


def spmd_pipeline(layer_fn, stage_params, x, mesh, axis_name="pp",
                  num_microbatches=None, extra_manual_axes=(),
                  microbatch_spec=None):
    """Apply L stacked layers to x through an S-stage pipeline.

    layer_fn(p_layer, x_mb) -> x_mb applies ONE layer to one microbatch.
    stage_params: pytree with leading dims [S, L/S] (stack_stage_params).
    x: [B, ...] global batch; split into num_microbatches (default S)
    along dim 0.
    extra_manual_axes/microbatch_spec: extend the manual region (e.g.
    manual 'sp' with the sequence dim of the microbatch sharded) for
    layer bodies that issue their own collectives.

    Returns y: [B, ...] == layer_fn applied L times to each sample.
    """
    S = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    M = int(num_microbatches or S)
    B = x.shape[0]
    if B % M != 0:
        raise ValueError("batch %d must divide by num_microbatches %d"
                         % (B, M))
    mb = x.reshape((M, B // M) + x.shape[1:])
    mb_spec = microbatch_spec if microbatch_spec is not None else P()

    def per_stage(params_stage, mb_local):
        # leaves arrive as [1, L/S, ...]: drop the sharded stage dim
        params_stage = jax.tree.map(lambda a: a[0], params_stage)
        stage = jax.lax.axis_index(axis_name)
        n_stages = jax.lax.psum(1, axis_name)

        def apply_stage(h):
            def one_layer(h, p_layer):
                return layer_fn(p_layer, h), None
            h, _ = jax.lax.scan(one_layer, h, params_stage)
            return h

        def _varying(a):
            # freshly-created accumulators must be marked device-varying
            # over pp so the scan carry type matches its outputs (same
            # trick as ring.py ring_attention)
            try:
                return jax.lax.pcast(a, (axis_name,), to="varying")
            except (AttributeError, TypeError, ValueError):
                return a

        state = _varying(jnp.zeros_like(mb_local[0]))
        outs = _varying(jnp.zeros_like(mb_local))

        def tick(carry, t):
            state, outs = carry
            # stage 0 ingests microbatch t; others consume the rotated
            # activation from their left neighbour
            x_in = jnp.where(stage == 0,
                             jax.lax.dynamic_index_in_dim(
                                 mb_local, jnp.clip(t, 0, M - 1), 0,
                                 keepdims=False),
                             state)
            y = apply_stage(x_in)
            # the last stage finished microbatch t-(S-1) this tick
            oi = jnp.clip(t - (n_stages - 1), 0, M - 1)
            valid = jnp.logical_and(t >= n_stages - 1,
                                    stage == n_stages - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, oi, 0,
                                                keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, y, prev), oi, 0)
            state = ring_permute(y, axis_name, 1)
            return (state, outs), None

        n_ticks = M + S - 1
        (state, outs), _ = jax.lax.scan(tick, (state, outs),
                                        jnp.arange(n_ticks))
        # only the last stage holds real outputs; broadcast around the
        # ring so the result is replicated over pp
        outs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)),
            axis_name)
        return outs

    param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    manual = set((axis_name,) + tuple(extra_manual_axes))
    from .ring import _shard_map
    out = _shard_map(per_stage, mesh=mesh,
                     in_specs=(param_specs, mb_spec),
                     out_specs=mb_spec,
                     axis_names=manual)(stage_params, mb)
    return out.reshape(x.shape)
