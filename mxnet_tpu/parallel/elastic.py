"""Elastic multi-host training: generation rendezvous, shrink, regrow.

The repo's pre-elastic failure story is "kill the whole world, restart
from the last periodic checkpoint" (examples/elastic_training.py) —
which discards everything the survivors still hold: their live
optimizer shards (parallel/fusion.py partitions optimizer state by
rank, the PAPERS.md cross-replica sharding), the replicated weights at
the CURRENT step rather than the last save, and the exact data-stream
position. This module is the survivor-side half of ROADMAP item 5's
multi-host story:

* **generation rendezvous** — every world membership is a numbered
  *generation*. A file sideband (``MXNET_ELASTIC_DIR``, defaulting to
  the watchdog's ``MXNET_OBS_WATCHDOG_DIR`` transport — same
  shared-directory contract, same atomic-replace writes) carries the
  generation record, per-rank heartbeats, and shrink/boundary records.
  No collective is ever used for membership: the sideband must keep
  working precisely when a peer has stopped answering collectives.
* **failure detection** — ranks heartbeat (`Heartbeat` thread, one
  atomic file replace per interval); a peer whose heartbeat is older
  than ``heartbeat_s * miss`` is presumed dead. A watchdog post-mortem
  file for the current generation (``postmortem.rank<r>.txt``) counts
  as independent evidence — a rank wedged in a collective is dead for
  membership purposes even while its heart still beats.
* **coordinated shrink** — on detection, every survivor (re-indexed
  over the sorted survivor set) captures its post-shrink shard of the
  training state into a per-rank sharded checkpoint
  (``models/checkpoint.save_shard_checkpoint``: replicated weights +
  the survivor's slice of the flat optimizer lanes + data cursor + RNG
  — layout derived from the deterministic ``fusion.plan_buckets``
  replan at the NEW world size), writes the generation-(g+1) shrink
  record, and exits with ``SHRINK_EXIT_CODE`` (44). The supervisor
  (``tools/elastic_launch.py``) relaunches at generation g+1, world
  N−k; a recovered host rejoins at the next generation boundary
  (``BOUNDARY_EXIT_CODE`` 45 → regrow to the full world).
* **exact resume** — ``resume_elastic`` loads the newest usable state
  (shard set or full checkpoint, whichever is newer), merge-on-load
  re-partitions the optimizer lanes for ANY new world size, the data
  cursor restores the iterator mid-epoch (io.py ``state_dict``), and
  ``MXNET_ELASTIC_KEEP_GLOBAL_BATCH=1`` compensates a shrunk world
  with gradient accumulation so global batch semantics survive.
  Correctness bar (tests/test_elastic.py, chaos_smoke --elastic): the
  post-shrink loss trajectory is bit-identical to a clean run started
  from the same step at the new world size, with zero skipped or
  replayed samples.

Observability: ``elastic.generation`` gauge, ``elastic.restart`` /
``elastic.shrink`` / ``elastic.regrow`` counters, and the
``elastic.time_to_recovery_ms`` histogram (PR 7 ``Histogram`` — merges
bucket-wise across ranks into the merged trace) observed by every
worker that comes up inside a recovery window.
"""

import json
import os
import threading
import time

from .. import _fastenv

__all__ = ["SHRINK_EXIT_CODE", "BOUNDARY_EXIT_CODE", "enabled",
           "elastic_dir", "rank_env", "world_env", "generation_env",
           "heartbeat_s", "miss_threshold", "keep_global_batch",
           "accumulation_factor", "read_generation", "write_generation",
           "heartbeat_path", "write_heartbeat", "read_heartbeats",
           "dead_ranks", "shrink_record_path", "write_shrink_record",
           "read_shrink_record", "quarantine_record_path",
           "write_quarantine_record", "read_quarantine_records",
           "quarantined_ranks", "prune_stale", "capture_rng",
           "restore_rng", "jsonable_cursor", "cursor_from_json",
           "Heartbeat", "ElasticCoordinator",
           "install_coordinator", "current_coordinator", "step_boundary",
           "make_accum_train_step", "observe_recovery"]

# supervisor-visible exit taxonomy (documented in docs/ROBUSTNESS.md;
# 43 = watchdog abort lives in observability/watchdog.py, 46 =
# quarantine in observability/integrity.py, 47 = structural OOM in
# observability/membudget.py — the supervisor relaunches with a doubled
# sticky accumulation factor)
SHRINK_EXIT_CODE = 44        # coordinated shrink: relaunch at g+1, N-k
BOUNDARY_EXIT_CODE = 45      # generation boundary, work remaining (regrow)

DEFAULT_HEARTBEAT_S = 2.0
DEFAULT_MISS = 3


# ------------------------------------------------------------ env knobs --

def elastic_dir():
    """MXNET_ELASTIC_DIR: the rendezvous sideband directory, resolved
    through the unified ``observability.sideband`` helper (the shared
    ``MXNET_OBS_SIDEBAND_DIR`` root serves it at ``<root>/elastic``).
    Falls back to MXNET_OBS_WATCHDOG_DIR — one shared directory serves
    both the watchdog check-in and the elastic membership protocol."""
    from ..observability import sideband as _sb
    return _sb.resolve("elastic") \
        or _fastenv.get("MXNET_OBS_WATCHDOG_DIR")


def enabled():
    """THE site guard (trainer step boundary): a sideband directory is
    configured. One `_fastenv` read when off."""
    return bool(elastic_dir())


def rank_env():
    """This process's elastic rank (the launcher's proc id)."""
    return int(_fastenv.get("MXNET_TPU_PROC_ID", "0") or 0)


def world_env():
    return int(_fastenv.get("MXNET_TPU_NUM_PROC", "1") or 1)


def generation_env():
    return int(_fastenv.get("MXNET_ELASTIC_GENERATION", "0") or 0)


def heartbeat_s():
    """MXNET_ELASTIC_HEARTBEAT_S: seconds between heartbeat writes."""
    try:
        return float(_fastenv.get("MXNET_ELASTIC_HEARTBEAT_S",
                                  DEFAULT_HEARTBEAT_S))
    except (TypeError, ValueError):
        return DEFAULT_HEARTBEAT_S


def miss_threshold():
    """MXNET_ELASTIC_MISS: missed heartbeat intervals before a peer is
    presumed dead (default 3)."""
    try:
        return max(int(_fastenv.get("MXNET_ELASTIC_MISS", DEFAULT_MISS)),
                   1)
    except (TypeError, ValueError):
        return DEFAULT_MISS


def keep_global_batch():
    """MXNET_ELASTIC_KEEP_GLOBAL_BATCH=1: a shrunk world compensates
    with gradient accumulation so the global batch (and therefore the
    loss trajectory semantics) survives the world-size change."""
    v = _fastenv.get("MXNET_ELASTIC_KEEP_GLOBAL_BATCH")
    return v is not None and v not in ("", "0", "false", "False")


def accumulation_factor(base_world, world):
    """Microbatches per step so ``world`` ranks cover ``base_world``
    ranks' global batch. Raises when the shrunk world cannot tile the
    original batch evenly — silently changing the effective batch is
    exactly the bug this knob exists to prevent."""
    base_world, world = int(base_world), int(world)
    if world <= 0 or base_world <= 0:
        raise ValueError("world sizes must be positive (base=%d, now=%d)"
                         % (base_world, world))
    if base_world % world:
        raise ValueError(
            "MXNET_ELASTIC_KEEP_GLOBAL_BATCH: world %d cannot evenly "
            "cover the original world %d's global batch — choose a "
            "divisor world size or restart without compensation"
            % (world, base_world))
    return base_world // world


# ----------------------------------------------------- sideband records --

def _atomic_write_json(path, obj):
    tmp = os.path.join(os.path.dirname(path),
                       "." + os.path.basename(path) + ".tmp")
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_generation(d):
    """The current generation record ``{"generation", "world",
    "ranks", ...}`` or None."""
    return _read_json(os.path.join(d, "gen.json"))


def write_generation(d, generation, world, ranks=None, base_world=None,
                     since_wall=None):
    """Commit the generation record (atomic replace — the rendezvous
    'pointer'). ``since_wall`` stamps when the previous generation's
    failure was detected, so the first worker up can observe
    time-to-recovery."""
    os.makedirs(d, exist_ok=True)
    rec = {"generation": int(generation), "world": int(world),
           "ranks": list(range(world)) if ranks is None else list(ranks),
           "wall": time.time()}
    if base_world is not None:
        rec["base_world"] = int(base_world)
    if since_wall is not None:
        rec["since_wall"] = float(since_wall)
    _atomic_write_json(os.path.join(d, "gen.json"), rec)
    return rec


def heartbeat_path(d, rank, generation):
    return os.path.join(d, "hb.g%d.rank%d.json" % (generation, rank))


def write_heartbeat(d, rank, generation, step=None, wall=None):
    """One atomic heartbeat: wall time + the last completed step."""
    os.makedirs(d, exist_ok=True)
    _atomic_write_json(heartbeat_path(d, rank, generation),
                       {"rank": int(rank), "generation": int(generation),
                        "step": None if step is None else int(step),
                        "wall": time.time() if wall is None else wall})


def read_heartbeats(d, generation):
    """{rank: record} for every readable heartbeat of ``generation``."""
    out = {}
    try:
        names = os.listdir(d)
    except OSError:
        return out
    prefix = "hb.g%d.rank" % generation
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        rec = _read_json(os.path.join(d, name))
        if rec is not None:
            out[int(rec.get("rank", -1))] = rec
    return out


def _postmortem_ranks(d):
    """Ranks that left a watchdog post-mortem in the sideband — a rank
    wedged in a collective is dead for membership purposes even while
    its heartbeat thread still beats."""
    out = set()
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if name.startswith("postmortem.rank") and name.endswith(".txt"):
            try:
                out.add(int(name[len("postmortem.rank"):-len(".txt")]))
            except ValueError:
                continue
    return out


def dead_ranks(d, generation, world, self_rank, now=None,
               stale_s=None, grace_s=None):
    """Peers presumed dead: heartbeat missing/older than ``stale_s``
    (default heartbeat_s * miss) or a watchdog post-mortem on file.
    ``grace_s`` (default = stale_s) suppresses the missing-file verdict
    right after a generation starts, while peers are still coming up."""
    now = time.time() if now is None else now
    stale_s = heartbeat_s() * miss_threshold() if stale_s is None \
        else float(stale_s)
    grace_s = stale_s if grace_s is None else float(grace_s)
    beats = read_heartbeats(d, generation)
    gen = read_generation(d) or {}
    gen_wall = float(gen.get("wall", 0.0)) \
        if gen.get("generation") == generation else 0.0
    dead = set()
    for r in range(world):
        if r == self_rank:
            continue
        rec = beats.get(r)
        if rec is None:
            # never checked in: only counts as dead once the start-up
            # grace window (measured from the generation commit) passed
            if gen_wall and now - gen_wall > grace_s:
                dead.add(r)
            continue
        if now - float(rec.get("wall", 0.0)) > stale_s:
            dead.add(r)
    for r in _postmortem_ranks(d):
        if r != self_rank and r < world:
            dead.add(r)
    # a quarantine record is death evidence too: the rank judged
    # itself corrupt and is leaving (exit 46) — survivors need not
    # wait out the heartbeat staleness window
    for r in quarantined_ranks(d, generation):
        if r != self_rank and 0 <= r < world:
            dead.add(r)
    return dead


def shrink_record_path(d, generation):
    return os.path.join(d, "shrink.g%d.json" % generation)


def write_shrink_record(d, new_generation, survivors, dead, step,
                        base_world=None, wall=None, quarantined=None):
    """The coordinated-shrink proposal every survivor writes (same
    content from every writer — the atomic replace makes the last one
    win harmlessly): relaunch at ``new_generation`` with ``survivors``
    as the new world, resuming from ``step``. ``quarantined`` names
    the dead ranks that were integrity-quarantined (no shard capture
    happened — resume restores from a verified checkpoint)."""
    os.makedirs(d, exist_ok=True)
    rec = {"generation": int(new_generation),
           "survivors": sorted(int(r) for r in survivors),
           "dead": sorted(int(r) for r in dead),
           "world": len(survivors), "step": int(step),
           "wall": time.time() if wall is None else wall}
    if base_world is not None:
        rec["base_world"] = int(base_world)
    if quarantined:
        rec["quarantined"] = sorted(int(r) for r in quarantined)
    _atomic_write_json(shrink_record_path(d, new_generation), rec)
    return rec


def read_shrink_record(d, generation):
    return _read_json(shrink_record_path(d, generation))


def quarantine_record_path(d, generation, rank):
    return os.path.join(d, "quarantine.g%d.rank%d.json"
                        % (generation, rank))


def write_quarantine_record(d, rank, generation, record):
    """The integrity quarantine evidence (observability/integrity.py):
    the rank judged corrupt writes WHY before exiting 46 — survivors
    read it to skip capturing corrupt-descended state, the supervisor
    reads it for the cooldown list."""
    os.makedirs(d, exist_ok=True)
    _atomic_write_json(quarantine_record_path(d, generation, rank),
                       dict(record, rank=int(rank),
                            generation=int(generation)))


def read_quarantine_records(d, generation=None):
    """All readable quarantine records (of ``generation`` when
    given), as a list of dicts."""
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith("quarantine.g")
                and name.endswith(".json")):
            continue
        rec = _read_json(os.path.join(d, name))
        if rec is None:
            continue
        if generation is not None \
                and int(rec.get("generation", -1)) != int(generation):
            continue
        out.append(rec)
    return out


def quarantined_ranks(d, generation):
    return set(int(r.get("rank", -1))
               for r in read_quarantine_records(d, generation))


def prune_stale(d, generation):
    """Delete sideband state from generations BEFORE ``generation`` —
    heartbeats, shrink records, watchdog check-ins and post-mortems. A
    relaunch must never read a dead generation's membership as live
    (the satellite contract: ``install_emergency_checkpoint`` calls
    this through ``models/checkpoint``)."""
    if not d or not os.path.isdir(d):
        return 0
    removed = 0
    for name in os.listdir(d):
        doomed = False
        for prefix in ("hb.g", "shrink.g", "quarantine.g"):
            if name.startswith(prefix) and name.endswith(".json"):
                try:
                    g = int(name[len(prefix):].split(".")[0])
                except ValueError:
                    continue
                doomed = g < generation
        # the watchdog sideband carries no generation tag: any check-in
        # or post-mortem written before this generation's record is a
        # previous incarnation's state
        if name.startswith("wd.rank") or name.startswith("postmortem."):
            gen = read_generation(d)
            wall = float((gen or {}).get("wall", 0.0))
            try:
                doomed = wall > 0 and \
                    os.path.getmtime(os.path.join(d, name)) < wall
            except OSError:
                continue
        if doomed:
            try:
                os.remove(os.path.join(d, name))
                removed += 1
            except OSError:
                pass
    return removed


# ------------------------------------------------------------- cursors --

def jsonable_cursor(state):
    """io.py ``state_dict()`` payloads keep numpy arrays for hot-path
    cheapness; manifests are JSON. Arrays become ``{"__nd__": dtype,
    "data": nested lists}`` markers, reversibly."""
    import numpy as np
    if isinstance(state, np.ndarray):
        return {"__nd__": str(state.dtype), "data": state.tolist()}
    if isinstance(state, np.generic):
        return state.item()
    if isinstance(state, dict):
        return {k: jsonable_cursor(v) for k, v in state.items()}
    if isinstance(state, (list, tuple)):
        return [jsonable_cursor(v) for v in state]
    return state


def cursor_from_json(state):
    """Inverse of :func:`jsonable_cursor`."""
    import numpy as np
    if isinstance(state, dict):
        if set(state) == {"__nd__", "data"}:
            return np.asarray(state["data"],
                              dtype=np.dtype(state["__nd__"]))
        return {k: cursor_from_json(v) for k, v in state.items()}
    if isinstance(state, list):
        return [cursor_from_json(v) for v in state]
    return state


# ------------------------------------------------------------------ rng --

def capture_rng(rng=None):
    """JSON-able snapshot of a numpy RandomState (default: the global
    numpy stream the shuffling iterators draw from)."""
    import numpy as np
    state = (rng.get_state() if rng is not None
             else np.random.get_state())
    name, keys, pos, has_gauss, cached = state
    return {"name": str(name), "keys": [int(k) for k in keys],
            "pos": int(pos), "has_gauss": int(has_gauss),
            "cached": float(cached)}


def restore_rng(snap, rng=None):
    """Inverse of :func:`capture_rng`."""
    import numpy as np
    state = (snap["name"], np.asarray(snap["keys"], np.uint32),
             int(snap["pos"]), int(snap["has_gauss"]),
             float(snap["cached"]))
    if rng is not None:
        rng.set_state(state)
        return rng
    np.random.set_state(state)
    return None


# -------------------------------------------------------------- threads --

class Heartbeat(threading.Thread):
    """Daemon heartbeat writer: one atomic file replace per interval.
    ``beat(step)`` from the training loop refreshes immediately and
    records the last completed step (the shrink record's resume
    point)."""

    def __init__(self, d, rank, generation, interval=None):
        super().__init__(name="mxnet-elastic-heartbeat", daemon=True)
        self.dir = d
        self.rank = int(rank)
        self.generation = int(generation)
        self.interval = heartbeat_s() if interval is None \
            else float(interval)
        self.step = None
        self._stop = threading.Event()
        self._last_write = 0.0
        self.beat()

    def beat(self, step=None):
        """Record liveness. Called from the training loop per step:
        the file write is throttled to half the interval (the thread
        covers the cadence), so a ms-scale step never pays a file
        replace per iteration."""
        if step is not None:
            self.step = int(step)
        now = time.time()
        if now - self._last_write < self.interval / 2.0:
            return
        self._last_write = now
        try:
            write_heartbeat(self.dir, self.rank, self.generation,
                            step=self.step, wall=now)
        except OSError:                 # sideband is best-effort
            pass

    def run(self):
        while not self._stop.wait(self.interval):
            self._last_write = 0.0      # thread beats are never skipped
            self.beat()

    def stop(self):
        self._stop.set()


class ElasticCoordinator(object):
    """One rank's view of the elastic protocol: heartbeat out, watch
    the peers, and on a detected death run the coordinated shrink —
    capture this survivor's shard of the training state, commit the
    g+1 shrink record, and leave with ``SHRINK_EXIT_CODE``.

    ``state()`` must return the provider dict
    ``models/checkpoint.install_emergency_checkpoint`` takes (``cfg`` /
    ``params`` / ``momentum`` / ``step`` and optionally ``cursor`` /
    ``metadata``) reflecting the last COMPLETED step — it is called
    from the monitor thread while the main thread may be wedged in a
    collective the dead peer will never join, so the state must be
    materialized and never donated to an in-flight dispatch.

    ``clock`` / ``exit`` / ``monitor`` are injectable for tests (fake
    time, captured exits, manual ``check()``)."""

    def __init__(self, ckpt_dir, state, d=None, rank=None, world=None,
                 generation=None, base_world=None, clock=time.time,
                 exit=None, monitor=True, interval=None, stale_s=None):
        self.ckpt_dir = ckpt_dir
        self.state = state
        self.dir = d or elastic_dir()
        if not self.dir:
            raise ValueError("elastic rendezvous needs MXNET_ELASTIC_DIR "
                             "(or MXNET_OBS_WATCHDOG_DIR) set")
        self.rank = rank_env() if rank is None else int(rank)
        self.world = world_env() if world is None else int(world)
        self.generation = generation_env() if generation is None \
            else int(generation)
        gen = read_generation(self.dir) or {}
        self.base_world = int(base_world if base_world is not None
                              else gen.get("base_world", self.world))
        self.clock = clock
        self._exit = exit
        self._stale_s = stale_s
        self._monitor = None
        self._shrunk = threading.Event()
        self.heartbeat = Heartbeat(self.dir, self.rank, self.generation,
                                   interval=interval)
        prune_stale(self.dir, self.generation)
        self._obs_generation()
        if monitor:
            self.heartbeat.start()
            self._monitor = threading.Thread(
                target=self._watch, name="mxnet-elastic-monitor",
                daemon=True)
            self._monitor.start()

    # ------------------------------------------------------ membership --
    def beat(self, step=None):
        self.heartbeat.beat(step)

    def dead(self, now=None):
        return dead_ranks(self.dir, self.generation, self.world,
                          self.rank, now=now, stale_s=self._stale_s)

    def check(self, now=None):
        """One membership check; runs the coordinated shrink when a
        peer died. Returns the dead set (empty when healthy)."""
        if self.world <= 1:
            return set()
        dead = self.dead(now)
        if dead:
            self.shrink(dead)
        return dead

    # ---------------------------------------------------------- shrink --
    def shrink(self, dead):
        """The survivor-side capture: sharded emergency checkpoint at
        the NEW world size, shrink record, exit 44. Idempotent —
        concurrent detection from the monitor thread and the step
        boundary runs it once."""
        if self._shrunk.is_set():
            return
        self._shrunk.set()
        survivors = sorted(set(range(self.world)) - set(dead))
        new_rank = survivors.index(self.rank)
        st = self.state()
        step = int(st.get("step", 0))
        quarantined = sorted(set(dead)
                             & quarantined_ranks(self.dir,
                                                 self.generation))
        from ..observability import core as _obs
        from ..observability import events as _events
        if _obs.enabled():
            _obs.counter("elastic.shrink").add(1)
            _obs.record_instant(
                "elastic.shrink", cat="elastic",
                args={"generation": self.generation,
                      "dead": sorted(int(r) for r in dead),
                      "quarantined": quarantined,
                      "survivors": survivors, "step": step})
            _events.event("elastic", change="shrink",
                          generation=self.generation,
                          to_generation=self.generation + 1,
                          dead=sorted(int(r) for r in dead),
                          world=len(survivors), step=step)
        print("[elastic] rank %d g%d: peer(s) %s dead — capturing "
              "shard %d/%d at step %d and leaving for generation %d"
              % (self.rank, self.generation,
                 sorted(int(r) for r in dead), new_rank,
                 len(survivors), step, self.generation + 1),
            flush=True)
        from ..models import checkpoint as ckpt
        if quarantined:
            # the dead peer was QUARANTINED for silent corruption: the
            # survivors' live state may descend from the poisoned
            # all-reduce, so it must not become the resume point — no
            # shard capture; resume falls back to the last VERIFIED
            # checkpoint (models/checkpoint verify-on-load lineage)
            print("[elastic] rank %d g%d: dead peer(s) %s quarantined "
                  "for corruption — skipping shard capture; resume "
                  "restores from the last verified checkpoint"
                  % (self.rank, self.generation, quarantined),
                  flush=True)
        else:
            try:
                ckpt.save_shard_checkpoint(
                    self.ckpt_dir, st["cfg"], st["params"],
                    momentum=st.get("momentum"), step=step,
                    rank=new_rank, world=len(survivors),
                    generation=self.generation + 1,
                    cursor=st.get("cursor"), rng=st.get("rng"),
                    base_world=self.base_world,
                    metadata=dict(st.get("metadata") or {},
                                  shrink_from_world=self.world))
            except Exception:           # last gasp: report, still leave
                import traceback
                traceback.print_exc()
        try:
            write_shrink_record(self.dir, self.generation + 1,
                                survivors, dead, step,
                                base_world=self.base_world,
                                quarantined=quarantined)
        except OSError:
            pass
        self.heartbeat.stop()
        from ..observability import flight as _flight
        _flight.record_incident(
            "elastic.shrink", exit_code=SHRINK_EXIT_CODE,
            generation=self.generation, dead=sorted(dead),
            survivors=len(survivors), step=step,
            quarantined=sorted(quarantined or []))
        if self._exit is not None:
            self._exit(SHRINK_EXIT_CODE)
        else:                            # pragma: no cover - fatal
            import sys
            sys.stderr.flush()
            sys.stdout.flush()
            os._exit(SHRINK_EXIT_CODE)

    # -------------------------------------------------------- boundary --
    def leave_at_boundary(self):
        """Clean generation-boundary exit (work remaining): the
        supervisor regrows the world to full strength. The caller is
        responsible for having saved a resumable checkpoint first."""
        self._shrunk.set()      # disarm: leaving deliberately
        self.heartbeat.stop()
        from ..observability import core as _obs
        from ..observability import events as _events
        from ..observability import flight as _flight
        if _obs.enabled():
            _events.event("elastic", change="boundary",
                          generation=self.generation)
        _flight.record_incident(
            "elastic.boundary", exit_code=BOUNDARY_EXIT_CODE,
            generation=self.generation)
        if self._exit is not None:
            self._exit(BOUNDARY_EXIT_CODE)
        else:                            # pragma: no cover - fatal
            import sys
            sys.stderr.flush()
            sys.stdout.flush()
            os._exit(BOUNDARY_EXIT_CODE)

    def stop(self):
        """Clean shutdown (job complete / caller-managed exit): ends
        the heartbeat AND the monitor, and disarms shrink — a peer
        that disappears after this rank finished is not a failure this
        rank should react to."""
        self._shrunk.set()
        self.heartbeat.stop()

    # ------------------------------------------------------------- obs --
    def _obs_generation(self):
        from ..observability import core as _obs
        if _obs.enabled():
            _obs.gauge("elastic.generation").set(self.generation)
            _obs.gauge("elastic.world").set(self.world)

    def _watch(self):                    # pragma: no cover - timing
        poll = max(0.05, self.heartbeat.interval / 2.0)
        while not self._shrunk.is_set():
            time.sleep(poll)
            try:
                self.check()
            except Exception:            # never take the process down
                pass


# --------------------------------------------------- module coordinator --

_installed = [None]


def install_coordinator(coord):
    """Register the process coordinator so framework step boundaries
    (gluon Trainer, training loops calling ``step_boundary``) drive
    the membership protocol without holding a reference."""
    _installed[0] = coord
    return coord


def current_coordinator():
    return _installed[0]


_env_beat = [0.0]                      # throttle for the env-only path


def step_boundary(step=None):
    """The per-step elastic hook: heartbeat + membership check when a
    coordinator is installed, bare heartbeat-by-env otherwise (write
    throttled to half the heartbeat interval). One guarded
    ``enabled()`` branch when elastic is off (the PR 2 cost contract —
    callers guard too)."""
    if not enabled():
        return
    coord = _installed[0]
    if coord is not None:
        coord.beat(step)
        coord.check()
        return
    now = time.time()
    if now - _env_beat[0] < heartbeat_s() / 2.0:
        return
    _env_beat[0] = now
    d = elastic_dir()
    try:
        write_heartbeat(d, rank_env(), generation_env(), step=step)
    except OSError:
        pass


def observe_recovery(generation=None, d=None):
    """Observe time-to-recovery when this worker came up inside a
    recovery window: the shrink/generation record carries the wall
    time the failure was detected (``since_wall`` / shrink ``wall``);
    now − then lands in the ``elastic.time_to_recovery_ms`` histogram
    (bucket-wise mergeable across ranks — PR 7) and the
    ``elastic.restart``/``elastic.regrow`` counters. Returns the
    milliseconds observed, or None outside a recovery."""
    d = d or elastic_dir()
    generation = generation_env() if generation is None else generation
    if not d or generation <= 0:
        return None
    since = None
    kind = "restart"
    rec = read_shrink_record(d, generation)
    if rec is not None:
        since = float(rec.get("wall", 0.0)) or None
        kind = "shrink"
    gen = read_generation(d)
    if gen is not None and gen.get("generation") == generation:
        since = float(gen.get("since_wall", 0.0)) or since
        if rec is None and gen.get("world", 0) > \
                (read_shrink_record(d, generation - 1) or {}).get(
                    "world", gen.get("world", 0)):
            kind = "regrow"
    if since is None:
        return None
    ms = max((time.time() - since) * 1e3, 0.0)
    from ..observability import core as _obs
    if _obs.enabled():
        _obs.histogram("elastic.time_to_recovery_ms", "ms").observe(ms)
        _obs.counter("elastic.restart").add(1)
        if kind == "regrow":
            _obs.counter("elastic.regrow").add(1)
        _obs.gauge("elastic.generation").set(generation)
        _obs.record_instant("elastic.recovered", cat="elastic",
                            args={"generation": generation,
                                  "kind": kind,
                                  "ms": round(ms, 3)})
        from ..observability import events as _events
        _events.event("elastic", change=kind or "recovered",
                      generation=generation, ms=round(ms, 3))
    return ms


# -------------------------------------------- accumulation compensation --

def make_accum_train_step(cfg, mesh=None, lr=1e-2, accum=1,
                          donate=False):
    """``models/transformer.make_train_step`` with gradient
    accumulation: the step takes tokens ``[accum, B, T]``, averages
    the ``accum`` microbatch gradients, and applies ONE optimizer
    update — so a world shrunk by k can keep the original global batch
    (``accumulation_factor``) at k× microbatches per step.

    ``accum=1`` reduces to the same math as ``make_train_step`` (a
    single-element mean is the identity). Donation is OFF by default:
    elastic capture reads the last completed step's state from a
    monitor thread while the next dispatch may be in flight, and a
    donated buffer is exactly the state that would no longer exist.
    Returns ``(params, momentum, mean_loss)``."""
    import jax
    import jax.numpy as jnp
    from ..models.transformer import loss_fn

    accum = int(accum)
    if accum < 1:
        raise ValueError("accum must be >= 1, got %d" % accum)

    def step(params, momentum, tokens):
        def micro(carry, tok):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tok, cfg, mesh)
            g_sum, l_sum = carry
            g_sum = jax.tree.map(jnp.add, g_sum, grads)
            return (g_sum, l_sum + loss), None

        zero = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.result_type(p.dtype,
                                                         jnp.float32)),
            params)
        (g_sum, l_sum), _ = jax.lax.scan(micro, (zero, jnp.float32(0.0)),
                                         tokens)
        scale = 1.0 / accum
        grads = jax.tree.map(lambda g: g * scale, g_sum)
        new_m = jax.tree.map(lambda m, g: 0.9 * m + g, momentum, grads)
        new_p = jax.tree.map(lambda p, m: p - lr * m.astype(p.dtype),
                             params, new_m)
        return new_p, new_m, l_sum * scale

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())
