"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context capability the reference lacks entirely (SURVEY §5
"Long-context / sequence parallelism: Absent"): sequences are sharded
over the 'sp' mesh axis and attention runs blockwise, rotating K/V
shards around the ring with lax.ppermute so no device ever materialises
the full sequence. Softmax is accumulated in flash-attention style
(running max / running sum), so results match full attention to fp
tolerance.

ICI mapping: each step overlaps the Q·K/softmax/PV block compute with a
neighbour ppermute of the K/V block (XLA schedules the collective-
permute concurrently with the matmuls, which is the whole point of the
ring schedule on TPU).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:                                    # jax >= 0.5 top-level alias
    _shard_map_impl = jax.shard_map
    _SMAP_NEW_API = True
except AttributeError:                  # 0.4.x experimental home
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SMAP_NEW_API = False


def _shard_map(f, mesh, in_specs, out_specs, axis_names=None,
               check_vma=None):
    """shard_map across jax API generations: new jax spells partial
    manual as ``axis_names={...}`` and the checker ``check_vma``; 0.4.x
    spells them ``auto=<complement>`` and ``check_rep``."""
    if _SMAP_NEW_API:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        if check_vma is not None:
            kw["check_vma"] = check_vma
        try:
            return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, **kw)
        except TypeError:               # pre-check_vma new API
            kw.pop("check_vma", None)
            return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, **kw)
    kw = {}
    # 0.4.x partial-auto shard_map lowers axis_index to a PartitionId
    # instruction SPMD partitioning rejects; since the non-manual axes
    # never appear in these call sites' specs (data is replicated over
    # them), running fully manual is equivalent — collectives still
    # only reference the named axes.
    if check_vma is not None:
        kw["check_rep"] = check_vma
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)


from . import ring_permute
from ..observability import chaos as _chaos
from ..observability import watchdog as _wd

__all__ = ["ring_attention", "local_attention_block",
           "ring_attention_sharded", "sp_flash_decode"]


def _watched_dispatch(name, fn, *args, **info):
    """Run one collective program under the hang watchdog. With the
    watchdog off (the default) this is a single guarded branch around a
    plain call; armed, completion is awaited inside the watched window
    so a rank stuck in the ring's ppermute/psum rendezvous produces a
    post-mortem instead of a silent stall. The chaos site of the same
    name can delay/hang/fail the dispatch for the injection harness."""
    if not _wd.enabled():
        if _chaos.enabled():
            _chaos.fire(name, **{k: str(v) for k, v in info.items()})
        return fn(*args)
    with _wd.watch(name, **info):
        if _chaos.enabled():
            _chaos.fire(name, **{k: str(v) for k, v in info.items()})
        out = fn(*args)
        jax.block_until_ready(out)
    return out

_NEG_INF = -1e30


def local_attention_block(q, k, v, q_offset, kv_offset, causal, scale,
                          carry=None, use_flash_kernel=False, vma=None):
    """One flash-attention block update.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]. Offsets are the global
    positions of element 0 of the q/kv blocks (for causal masking).
    carry = (o, m, l) running output/max/denominator, or None to start.

    use_flash_kernel routes the block through the Pallas streamed
    kernel (kernels/flash_attention.flash_carry_block): the [Tq, Tk]
    score matrix then never exists in HBM, so per-device shards are
    bounded by HBM capacity rather than the score-matrix footprint.
    Requires shard lengths divisible by the kernel blocks (clamped to
    the shard).
    """
    if use_flash_kernel:
        return _flash_block(q, k, v, q_offset, kv_offset, causal, carry,
                            vma)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(Tq)
        kv_pos = kv_offset + jnp.arange(Tk)
        mask = q_pos[:, None] >= kv_pos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    if carry is None:
        o = jnp.zeros((B, Tq, H, D), dtype=jnp.float32)
        m = jnp.full((B, H, Tq), _NEG_INF, dtype=jnp.float32)
        l = jnp.zeros((B, H, Tq), dtype=jnp.float32)
    else:
        o, m, l = carry
    m_new = jnp.maximum(m, scores.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = alpha * l + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = alpha.transpose(0, 2, 1)[..., None] * o + pv
    return o_new, m_new, l_new


def _flash_block(q, k, v, q_offset, kv_offset, causal, carry, vma=None):
    """local_attention_block via the Pallas carry kernel; carries the
    same (o [B,Tq,H,D] f32, m/l [B,H,Tq] f32) layout as the jnp path."""
    from ..kernels.flash_attention import flash_carry_block
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(
        B * x.shape[2], x.shape[1], D)
    if carry is None:
        o = jnp.zeros((B * H, Tq, D), jnp.float32)
        m = jnp.full((B * H, Tq), _NEG_INF, jnp.float32)
        l = jnp.zeros((B * H, Tq), jnp.float32)
        if vma:
            # fresh accumulators are mesh-invariant while q/k/v are
            # sp-varying; pallas + the vma checker need them to agree
            def _v(x):
                try:
                    return jax.lax.pcast(x, tuple(vma), to="varying")
                except (AttributeError, TypeError, ValueError):
                    return x
            o, m, l = _v(o), _v(m), _v(l)
    else:
        o_c, m_c, l_c = carry
        o = to_bh(o_c)
        m = m_c.reshape(B * H, Tq)
        l = l_c.reshape(B * H, Tq)
    o, m, l = flash_carry_block(to_bh(q), to_bh(k), to_bh(v), o, m, l,
                                q_offset, kv_offset, causal,
                                vma=None if vma is None
                                else tuple(sorted(vma)))
    o_out = o.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    return o_out, m.reshape(B, H, Tq), l.reshape(B, H, Tq)


def ring_attention(q, k, v, axis_name="sp", causal=True,
                   use_flash_kernel=False):
    """Blockwise ring attention. Must run inside shard_map (or pmap) with
    the sequence dimension sharded over `axis_name`.

    q, k, v: [B, T_local, H, D] — this device's sequence shard.
    Returns [B, T_local, H, D].
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    T = q.shape[1]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    q_offset = idx * T

    def body(i, carry):
        o, m, l, k_blk, v_blk, kv_idx = carry
        # rotate K/V to the next device; we now hold our left
        # neighbour's block, whose global index is one lower (mod n)
        k_blk = ring_permute(k_blk, axis_name)
        v_blk = ring_permute(v_blk, axis_name)
        kv_idx = (kv_idx - 1) % n
        o, m, l = local_attention_block(
            q, k_blk, v_blk, q_offset, kv_idx * T, causal, scale,
            carry=(o, m, l), use_flash_kernel=use_flash_kernel,
            vma=(axis_name,))
        return (o, m, l, k_blk, v_blk, kv_idx)

    B, T, H, D = q.shape

    def _varying(x):
        # mark freshly-created accumulators as device-varying so the
        # fori_loop carry type matches its (sp-varying) outputs
        try:
            return jax.lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError, ValueError):
            return x  # already varying (or pcast not available)

    # own block first (no permute), then n-1 rotate+accumulate rounds —
    # exactly n-1 collective-permutes per call
    o0, m0, l0 = local_attention_block(q, k, v, q_offset, idx * T, causal,
                                       scale, carry=None,
                                       use_flash_kernel=use_flash_kernel,
                                       vma=(axis_name,))
    init = (_varying(o0), _varying(m0), _varying(l0), k, v, idx)
    o, m, l, _, _, _ = jax.lax.fori_loop(0, n - 1, body, init)
    # fully-masked rows (can't happen for causal same-length rings, but
    # guard anyway) would have l == 0
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=True,
                           batch_axis=None, use_flash_kernel=False):
    """Convenience wrapper: apply ring attention to GLOBAL arrays
    [B, T, H, D] whose T dim is (or will be) sharded over `axis_name`.
    Usable inside jit — shard_map is restricted to the sp (and optional
    batch) mesh axes, all other mesh axes stay auto-sharded."""
    spec = P(batch_axis, axis_name, None, None)
    manual = (axis_name,) if batch_axis is None else (axis_name, batch_axis)
    kw = {}
    if use_flash_kernel:
        interpret = jax.default_backend() != "tpu"
        partial_manual = bool(set(mesh.axis_names) - set(manual))
        if interpret and partial_manual:
            # interpret-mode pallas (CPU testing) cannot run under a
            # vma-checked partially-manual shard_map (jax interpreter
            # lowers block fetches to dynamic_slice with mesh-invariant
            # indices). On real TPU the compiled kernel carries vma
            # annotations and this limitation does not apply; on CPU
            # keep the numerics via the jnp blockwise path.
            use_flash_kernel = False
        elif interpret:
            # fully-manual mesh: disable the checker instead (outputs
            # are per-shard by construction)
            kw["check_vma"] = False
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal,
                           use_flash_kernel=use_flash_kernel)
    smapped = _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names=set(manual), **kw)
    # jit the mapped program: eager shard_map lacks rules for the ring
    # loop on older jax, and compiled is what a train step wants anyway
    return _watched_dispatch(
        "ring.attention", jax.jit(smapped), q, k, v,
        axis=axis_name, shape=str(tuple(q.shape)))


def sp_flash_decode(q, k_cache, v_cache, lengths, mesh, axis_name="sp",
                    batch_axis=None, block_k=None, interpret=None,
                    use_pallas=None):
    """Sequence-parallel DECODING: single-token attention against a KV
    cache sharded over `axis_name` along its sequence dim.

    q: [B, H, D] (replicated over sp); k_cache/v_cache: [B, Tmax, H, D]
    with Tmax sharded over sp; lengths: [B] (or scalar) GLOBAL valid
    lengths. Each device computes (o, lse) over its cache slice with
    the length clipped to the slice, then the partial results combine
    with their log-sum-exp weights — one psum over sp instead of
    gathering the cache (flash-decoding decomposition; the
    long-context serving complement of ring_attention).

    The per-shard compute defaults to dense_decode_with_lse (plain
    XLA): decode reads [1, T] scores, so there is nothing for a flash
    schedule to tile away, and the chip A/B measured the Pallas decode
    kernel ~5x slower at serving shapes (BENCH_TABLE decode_dense vs
    decode_flash). `use_pallas=True` (or MXNET_SP_DECODE_PALLAS=1)
    restores the kernel path."""
    from ..kernels.flash_attention import (dense_decode_with_lse,
                                           flash_decode_with_lse)

    explicit_pallas = use_pallas is True
    if use_pallas is None:
        import os
        use_pallas = os.environ.get(
            "MXNET_SP_DECODE_PALLAS", "0").lower() in ("1", "true")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret:
        if explicit_pallas:
            # deliberate fallback must be distinguishable from
            # misconfiguration (ADVICE r5): the caller asked for the
            # kernel by argument and is getting plain XLA instead
            import warnings
            warnings.warn(
                "sp_flash_decode: use_pallas=True ignored — interpret "
                "mode is active (backend %r is not TPU), and "
                "interpret-mode pallas cannot run under a partially-"
                "manual shard_map; computing with dense_decode_with_lse "
                "instead" % jax.default_backend(), stacklevel=2)
        use_pallas = False   # interpret-mode pallas can't run under a
        #                      partially-manual shard_map

    def local(q_l, k_l, v_l, len_l):
        idx = jax.lax.axis_index(axis_name)
        t_shard = k_l.shape[1]
        local_len = jnp.clip(len_l - idx * t_shard, 0, t_shard)
        if use_pallas:
            o_i, lse_i = flash_decode_with_lse(
                q_l, k_l, v_l, local_len, block_k=block_k,
                interpret=False)
            o_i = o_i.astype(jnp.float32)
        else:
            # zero-valid-key shards come back o=0, lse~-1e30 and drop
            # out of the combine below
            o_i, lse_i = dense_decode_with_lse(q_l, k_l, v_l, local_len)
        # combine partial softmaxes across the sp shards
        m_g = jax.lax.pmax(lse_i, axis_name)
        w = jnp.exp(lse_i - m_g)
        num = jax.lax.psum(w[..., None] * o_i, axis_name)
        den = jax.lax.psum(w, axis_name)
        return (num / jnp.maximum(den, 1e-30)[..., None]).astype(q_l.dtype)

    qspec = P(batch_axis, None, None)
    cspec = P(batch_axis, axis_name, None, None)
    lspec = P(batch_axis)
    manual = {axis_name} if batch_axis is None else {axis_name, batch_axis}
    b = q.shape[0]
    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (b,))
    smapped = _shard_map(
        local, mesh=mesh, in_specs=(qspec, cspec, cspec, lspec),
        out_specs=qspec, axis_names=manual)
    return _watched_dispatch(
        "ring.sp_flash_decode", jax.jit(smapped),
        q, k_cache, v_cache, lengths,
        axis=axis_name, batch=q.shape[0])
