"""Parallelism primitives — the TPU-native communication substrate.

Replaces the reference's three comm stacks (src/kvstore/comm.h CPU/P2P
tree reduce, kvstore_nccl.h NCCL, kvstore_dist.h ps-lite) with one layer:
jax.sharding Mesh + XLA collectives (psum/all_gather/reduce_scatter/
ppermute) over ICI within a slice and DCN across slices.

Axis convention (used across the framework):
  'dp' — data parallel          'tp' — tensor (model) parallel
  'pp' — pipeline parallel      'sp' — sequence/context parallel
  'ep' — expert parallel

The reference has only DP (kvstore) + manual-placement model parallelism
(group2ctx, graph_executor.cc:997). TP/PP/SP/EP here are capability
extensions enabled by GSPMD (SURVEY §2.3 'NOT PRESENT' row).
"""

from contextlib import contextmanager

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["Mesh", "NamedSharding", "P", "make_mesh", "current_mesh",
           "use_mesh", "set_mesh", "shard", "replicate", "all_reduce",
           "all_gather", "reduce_scatter", "ring_permute", "device_count",
           "init_distributed", "fusion", "elastic",
           "bucketed_all_reduce"]

_CURRENT_MESH = None


def init_distributed(coordinator=None, num_processes=None, process_id=None):
    """Join a multi-host SPMD job (the tools/launch.py bootstrap).

    Replaces the reference's ps-lite scheduler rendezvous
    (DMLC_PS_ROOT_URI / DMLC_ROLE env contract consumed by
    tools/launch.py + dmlc_tracker): every worker calls in with a shared
    coordinator address and its process id, after which jax.devices()
    spans all hosts and the mesh/collective layer works unchanged.
    Arguments default to the MXNET_TPU_* environment set by the
    launcher. No-op when the job has a single process and no
    coordinator is configured.
    """
    import os
    coordinator = coordinator or os.environ.get("MXNET_TPU_COORDINATOR")
    num_processes = int(num_processes or
                        os.environ.get("MXNET_TPU_NUM_PROC", "1"))
    process_id = int(process_id if process_id is not None
                     else os.environ.get("MXNET_TPU_PROC_ID", "0"))
    if coordinator is None and num_processes == 1:
        return False
    # honor JAX_PLATFORMS before the backend initializes: discovery
    # plugins can override the env var (the tests/conftest.py gotcha),
    # and the local launcher depends on its cpu pin sticking
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if (os.environ.get("JAX_PLATFORMS") or "").startswith("cpu"):
        # cross-process collectives on the CPU backend need the gloo
        # implementation (XLA:CPU's default rejects multiprocess
        # computations); must be set before the backend initializes
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:       # jaxlib built without gloo: leave as-is
            pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def device_count():
    return jax.device_count()


def make_mesh(axes=None, devices=None):
    """Build a Mesh from an axis-name -> size dict.

    make_mesh({'dp': 4, 'tp': 2}) lays 8 devices out as a 4x2 grid.
    Sizes of -1 are inferred (at most one). Defaults to pure DP over all
    devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    assert int(np.prod(sizes)) == n, \
        "mesh axes %s don't cover %d devices" % (dict(zip(names, sizes)), n)
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def current_mesh():
    """The active mesh (creates a default all-DP mesh on first use)."""
    global _CURRENT_MESH
    if _CURRENT_MESH is None:
        _CURRENT_MESH = make_mesh()
    return _CURRENT_MESH


def set_mesh(mesh):
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


@contextmanager
def use_mesh(mesh):
    global _CURRENT_MESH
    prev = _CURRENT_MESH
    _CURRENT_MESH = mesh
    try:
        yield mesh
    finally:
        _CURRENT_MESH = prev


def shard(x, spec, mesh=None):
    """Place an array (jax.Array / NDArray data) with a PartitionSpec."""
    mesh = mesh or current_mesh()
    data = x._data if hasattr(x, "_data") else x
    return jax.device_put(data, NamedSharding(mesh, spec))


def replicate(x, mesh=None):
    return shard(x, P(), mesh)


# ---------------------------------------------------------------------
# Collectives — inside shard_map/pjit these lower to ICI/DCN collectives.
# Outside a mapped context they operate on sharded global arrays via jnp
# (XLA inserts the communication).
# ---------------------------------------------------------------------

def all_reduce(x, axis_name="dp"):
    """psum over a mesh axis (usable inside shard_map)."""
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name="dp", axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="dp", scatter_dimension=0):
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


def ring_permute(x, axis_name, shift=1):
    """ppermute by `shift` around the ring — building block for ring
    attention / pipeline transfers."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm=perm)


# bucketed gradient fusion (one psum per ~25 MB bucket instead of one
# per array) — importable as mxnet_tpu.parallel.fusion; the in-jit
# entry point re-exported here for train-step authors
from . import fusion                                    # noqa: E402
from .fusion import bucketed_all_reduce                 # noqa: E402,F401
