"""Misc utilities (reference: python/mxnet/util.py)."""

import functools
import inspect
import threading

__all__ = ["makedirs", "use_np_shape", "is_np_shape", "set_np_shape",
           "np_shape", "wraps_safely", "set_np_array", "is_np_array",
           "np_array", "set_np", "reset_np", "use_np", "use_np_array",
           "get_gpu_count", "get_gpu_memory", "set_module"]

_np_shape_flag = threading.local()


def makedirs(d):
    import os
    os.makedirs(d, exist_ok=True)


def set_np_shape(active):
    """Enable/disable NumPy shape semantics (zero-dim/zero-size arrays).
    The TPU build always supports them natively; the flag is kept for
    source compatibility and gates mx.np array creation defaults."""
    prev = getattr(_np_shape_flag, "value", False)
    _np_shape_flag.value = bool(active)
    return prev


def is_np_shape():
    return getattr(_np_shape_flag, "value", False)


class np_shape(object):
    """Context manager / decorator form of set_np_shape."""

    def __init__(self, active=True):
        self._active = active
        self._prev = None

    def __enter__(self):
        self._prev = set_np_shape(self._active)
        return self

    def __exit__(self, *exc):
        set_np_shape(self._prev)

    def __call__(self, func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            with np_shape(self._active):
                return func(*args, **kwargs)
        return wrapper



def wraps_safely(obj, attr_list=functools.WRAPPER_ASSIGNMENTS):
    """functools.wraps tolerant of missing attributes."""
    safe = [a for a in attr_list if hasattr(obj, a)]
    return functools.wraps(obj, assigned=safe)


_np_array_flag = threading.local()


def set_np_array(active):
    """Enable/disable NumPy-array semantics: when on, Gluon blocks
    return mx.np.ndarray outputs instead of classic NDArray (reference
    util.py set_np_array; both types share the same jax buffers here,
    so the switch only selects the wrapper)."""
    prev = getattr(_np_array_flag, "value", False)
    _np_array_flag.value = bool(active)
    return prev


def is_np_array():
    return getattr(_np_array_flag, "value", False)


def np_array(func=None, active=True):
    """Decorator/context flipping array semantics (reference np_array)."""
    class _Scope(object):
        def __enter__(self):
            self._prev = set_np_array(active)
            return self

        def __exit__(self, *exc):
            set_np_array(self._prev)

        def __call__(self, f):
            @functools.wraps(f)
            def wrapper(*args, **kwargs):
                with _Scope():
                    return f(*args, **kwargs)
            return wrapper
    scope = _Scope()
    return scope(func) if func is not None else scope


def set_np(shape=True, array=True):
    """Turn on both NumPy semantics flags (reference set_np)."""
    if not shape and array:
        raise ValueError("NumPy array semantics require NumPy shape "
                         "semantics")
    set_np_shape(shape)
    set_np_array(array)


def reset_np():
    """Back to classic semantics (reference reset_np)."""
    set_np_shape(False)
    set_np_array(False)


def use_np_array(func):
    """Class/function decorator applying np-array semantics (reference
    use_np_array)."""
    if inspect.isclass(func):
        for name, m in inspect.getmembers(func, predicate=callable):
            if name in ("forward", "hybrid_forward", "__call__"):
                setattr(func, name, np_array(m))
        return func
    return np_array(func)


def use_np(func):
    """use_np_shape + use_np_array combined (reference use_np)."""
    return use_np_array(use_np_shape(func) if not inspect.isclass(func)
                        else func)


def use_np_shape(func):
    """Decorator form applying np shape semantics (zero-dim shapes are
    always native here, so this only flips the compatibility flag)."""
    if isinstance(func, bool):          # legacy use_np_shape(True) scope
        return np_shape(func)
    if inspect.isclass(func):
        return func          # always-on natively
    return np_shape(True)(func)


def get_gpu_count():
    """Accelerator count (reference util.get_gpu_count reads CUDA; here
    the attached TPU/accelerator devices)."""
    import jax
    return len([d for d in jax.devices() if d.platform != "cpu"])


def get_gpu_memory(dev_id=0):
    """(free, total) bytes of accelerator dev_id when the backend
    exposes memory_stats; raises otherwise (parity with the reference's
    CUDA-only behavior)."""
    import jax
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if dev_id >= len(devs):
        raise ValueError("Invalid device id %d" % dev_id)
    stats = devs[dev_id].memory_stats()
    if not stats:
        raise RuntimeError("backend exposes no memory stats")
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    return total - used, total


def set_module(module):
    """Decorator overriding __module__ for doc purposes (reference)."""
    def deco(obj):
        if module is not None:
            obj.__module__ = module
        return obj
    return deco
