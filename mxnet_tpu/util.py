"""Misc utilities (reference: python/mxnet/util.py)."""

import functools
import inspect
import threading

__all__ = ["makedirs", "use_np_shape", "is_np_shape", "set_np_shape",
           "np_shape", "wraps_safely"]

_np_shape_flag = threading.local()


def makedirs(d):
    import os
    os.makedirs(d, exist_ok=True)


def set_np_shape(active):
    """Enable/disable NumPy shape semantics (zero-dim/zero-size arrays).
    The TPU build always supports them natively; the flag is kept for
    source compatibility and gates mx.np array creation defaults."""
    prev = getattr(_np_shape_flag, "value", False)
    _np_shape_flag.value = bool(active)
    return prev


def is_np_shape():
    return getattr(_np_shape_flag, "value", False)


class np_shape(object):
    """Context manager / decorator form of set_np_shape."""

    def __init__(self, active=True):
        self._active = active
        self._prev = None

    def __enter__(self):
        self._prev = set_np_shape(self._active)
        return self

    def __exit__(self, *exc):
        set_np_shape(self._prev)

    def __call__(self, func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            with np_shape(self._active):
                return func(*args, **kwargs)
        return wrapper


use_np_shape = np_shape


def wraps_safely(obj, attr_list=functools.WRAPPER_ASSIGNMENTS):
    """functools.wraps tolerant of missing attributes."""
    safe = [a for a in attr_list if hasattr(obj, a)]
    return functools.wraps(obj, assigned=safe)
