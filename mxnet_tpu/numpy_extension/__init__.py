"""mx.npx — NumPy-extension operators (reference:
python/mxnet/numpy_extension/ — neural-net ops with numpy-semantics
arrays, set_np/reset_np switches)."""

import jax
import jax.numpy as jnp

from .. import util as _util
from .. import ndarray as _classic
from ..numpy import ndarray as np_ndarray, _wrap, _unwrap

__all__ = ["set_np", "reset_np", "is_np_array", "use_np", "relu",
           "sigmoid", "softmax", "log_softmax", "topk", "pick",
           "one_hot", "gamma", "erf", "erfinv", "batch_dot",
           "reshape_like", "batch_flatten", "save", "load", "seed",
           "waitall"]

_np_array_active = [False]


def set_np(shape=True, array=True):
    """Enable NumPy semantics globally (reference npx.set_np)."""
    _util.set_np_shape(shape)
    _np_array_active[0] = array


def reset_np():
    _util.set_np_shape(False)
    _np_array_active[0] = False


def is_np_array():
    return _np_array_active[0]


class use_np(object):
    """Decorator/context enabling np semantics inside. Supports
    @use_np, @use_np(), and `with use_np():` forms."""

    def __init__(self, func=None):
        self._func = func

    def _snapshot(self):
        return (_np_array_active[0], _util.is_np_shape())

    def _restore(self, snap):
        _np_array_active[0] = snap[0]
        _util.set_np_shape(snap[1])

    def __call__(self, *args, **kwargs):
        if self._func is None:
            # @use_np() form: the single argument is the function
            if len(args) == 1 and callable(args[0]) and not kwargs:
                return use_np(args[0])
            raise TypeError("use_np() expects a callable to decorate")
        snap = self._snapshot()
        set_np()
        try:
            return self._func(*args, **kwargs)
        finally:
            self._restore(snap)

    def __enter__(self):
        self._prev = self._snapshot()
        set_np()
        return self

    def __exit__(self, *exc):
        self._restore(self._prev)


def relu(x):
    return _wrap(jnp.maximum(_unwrap(x), 0))


def sigmoid(x):
    return _wrap(jax.nn.sigmoid(_unwrap(x)))


def softmax(x, axis=-1):
    return _wrap(jax.nn.softmax(_unwrap(x), axis=axis))


def log_softmax(x, axis=-1):
    return _wrap(jax.nn.log_softmax(_unwrap(x), axis=axis))


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    d = _unwrap(data)
    d_move = jnp.moveaxis(d, axis, -1)
    if is_ascend:
        d_move = -d_move
    vals, idx = jax.lax.top_k(d_move, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "indices":
        return _wrap(idx)
    if ret_typ == "value":
        return _wrap(vals)
    # 'both' = [values, indices] (ordering_op-inl.h:62-63)
    return _wrap(vals), _wrap(idx)


def pick(data, index, axis=-1, keepdims=False):
    d, i = _unwrap(data), _unwrap(index).astype(jnp.int32)
    out = jnp.take_along_axis(d, jnp.expand_dims(i, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return _wrap(out)


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype=None):
    out = jax.nn.one_hot(_unwrap(data).astype(jnp.int32), depth,
                         dtype=dtype or jnp.float32)
    return _wrap(out * (on_value - off_value) + off_value)


def gamma(x):
    return _wrap(jnp.exp(jax.scipy.special.gammaln(_unwrap(x))))


def erf(x):
    return _wrap(jax.scipy.special.erf(_unwrap(x)))


def erfinv(x):
    return _wrap(jax.scipy.special.erfinv(_unwrap(x)))


def batch_dot(a, b, transpose_a=False, transpose_b=False):
    a, b = _unwrap(a), _unwrap(b)
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return _wrap(jnp.matmul(a, b))


def reshape_like(lhs, rhs):
    return _wrap(jnp.reshape(_unwrap(lhs), _unwrap(rhs).shape))


def batch_flatten(x):
    d = _unwrap(x)
    return _wrap(d.reshape(d.shape[0], -1))


def save(file, arr):
    from .. import ndarray as nd
    nd.save(file, {k: _classic.NDArray(_unwrap(v))
                   for k, v in arr.items()}
            if isinstance(arr, dict) else
            [_classic.NDArray(_unwrap(a)) for a in arr])


def load(file):
    from .. import ndarray as nd
    out = nd.load(file)
    if isinstance(out, dict):
        return {k: _wrap(v._data) for k, v in out.items()}
    return [_wrap(v._data) for v in out]


def seed(s):
    from .. import random as _rand
    _rand.seed(s)


def waitall():
    from .. import ndarray as nd
    nd.waitall()
