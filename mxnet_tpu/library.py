"""Dynamic operator libraries (reference: python/mxnet/library.py +
include/mxnet/lib_api.h — load .so files registering extra ops).

TPU-native equivalent: an "op library" is a python module that calls
mxnet_tpu.ops.register at import. load() imports such a module from a
file path; compiled CUDA .so op libraries are meaningless here."""

import importlib.util
import os

from .base import MXNetError

__all__ = ["load"]


def load(path, verbose=True):
    """Load an op-library python file (registers its ops on import)."""
    if not os.path.exists(path):
        raise MXNetError("library %s does not exist" % path)
    if path.endswith(".so") or path.endswith(".dylib"):
        raise MXNetError(
            "compiled CUDA op libraries are not loadable in the TPU "
            "build; ship the op as a python module that registers jax "
            "kernels via mxnet_tpu.ops.register")
    name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    before = set(_registered_ops())
    spec.loader.exec_module(module)
    if verbose:
        added = set(_registered_ops()) - before
        print("loaded library %s (registered ops: %s)"
              % (path, sorted(added) if added else "none"))
    return module


def _registered_ops():
    from . import ops
    return ops.list_ops() if hasattr(ops, "list_ops") else []
