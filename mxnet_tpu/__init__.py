"""mxnet_tpu — a TPU-native deep-learning framework with the MXNet 1.5 API.

Brand-new implementation (NOT a port): the compute path is JAX/XLA/Pallas,
parallelism is jax.sharding Mesh + collectives over ICI/DCN, and eager /
hybridized execution maps onto XLA tracing + jit instead of an async CUDA
dependency engine.

API surface mirrors the reference (nswamy/incubator-mxnet):
  python/mxnet/__init__.py — top-level namespaces nd, sym, gluon, module,
  autograd, optimizer, kvstore, io, metric, initializer, ...
"""

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import engine
from . import storage
from . import ops
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor
from . import lr_scheduler
from . import optimizer
from . import initializer
from . import initializer as init
from . import metric
from . import recordio
from . import image
from . import io
from . import kvstore
from . import callback
from . import model
from . import sparse
ndarray.sparse = sparse  # compressed-storage sparse module (nd.sparse)
ndarray.csr_matrix = sparse.csr_matrix
ndarray.row_sparse_array = sparse.row_sparse_array
from . import parallel
from . import module
mod = module  # reference alias (mx.mod)
from . import inspector
from .inspector import TensorInspector
from . import monitor
from .monitor import Monitor
from . import observability
from . import profiler
from . import runtime
from . import test_utils
from . import visualization
from . import operator
# the reference exposes custom ops as the `Custom` op in the nd namespace
# (src/operator/custom/custom.cc); symbolic Custom is unsupported — host
# callbacks cannot live inside a single compiled XLA graph (operator.py).
ndarray.Custom = operator.Custom
from . import registry
from . import rtc
from . import library
from . import libinfo
from . import util
from . import name
from .name import NameManager, Prefix
from . import attribute
from .attribute import AttrScope
from . import contrib
from . import log
from . import executor_manager
from . import kvstore_server
from . import torch
from . import utils
from . import models
from . import gluon
from . import rnn
from . import numpy as np
from . import numpy_extension as npx
