"""Custom Python operators (reference: python/mxnet/operator.py
CustomOp:428 / CustomOpProp:474 / register:694; C++ host
src/operator/custom/custom.cc runs the callbacks on a dedicated
thread).

TPU-native scope: custom ops execute EAGERLY on the host between XLA
computations (the autograd tape records their backward like any other
op). Inside hybridized/jit graphs they are not supported — a Python
callback inside a compiled TPU program would stall the device (the
reference has the same wart: custom ops break graph fusion and
cross-device async). Use nd.Custom / mx.operator for the eager path."""

from .base import MXNetError
from . import autograd

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered",
           "Custom"]

_REGISTRY = {}


class CustomOp(object):
    """Base class for custom eager operators."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Assign src to dst honouring the grad req."""
        if req in ("null", None):
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError("unknown req %s" % req)


class CustomOpProp(object):
    """Describes a custom op: arguments, outputs, shapes, types."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), \
            [in_shape[0]] * len(self.list_auxiliary_states())

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Decorator registering a CustomOpProp subclass under a name."""

    def do_register(prop_cls):
        assert issubclass(prop_cls, CustomOpProp), \
            "can only register subclass of CustomOpProp"
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered():
    return dict(_REGISTRY)


class _CustomFunction(autograd.Function):
    def __init__(self, op, prop, num_outputs):
        super(_CustomFunction, self).__init__()
        self._op = op
        self._prop = prop
        self._num_outputs = num_outputs
        self._in_data = None
        self._out_data = None

    def __call__(self, *inputs):
        # capture training state BEFORE Function.__call__ wraps forward in
        # autograd.pause() (which would make is_recording() always False)
        self._is_train = autograd.is_recording()
        return super(_CustomFunction, self).__call__(*inputs)

    def forward(self, *inputs):
        from . import ndarray as nd
        out_shapes = self._prop.infer_shape(
            [i.shape for i in inputs])[1]
        in_types = [i.dtype for i in inputs]
        out_types = self._prop.infer_type(in_types)[1]
        outputs = [nd.zeros(s, dtype=t)
                   for s, t in zip(out_shapes, out_types)]
        self._op.forward(is_train=self._is_train,
                         req=["write"] * len(outputs),
                         in_data=list(inputs), out_data=outputs, aux=[])
        self._in_data = list(inputs)
        self._out_data = outputs
        return outputs if len(outputs) > 1 else outputs[0]

    def backward(self, *out_grads):
        from . import ndarray as nd
        in_grads = [nd.zeros(i.shape, dtype=i.dtype)
                    for i in self._in_data]
        self._op.backward(req=["write"] * len(in_grads),
                          out_grad=list(out_grads),
                          in_data=self._in_data,
                          out_data=self._out_data,
                          in_grad=in_grads, aux=[])
        return in_grads if len(in_grads) > 1 else in_grads[0]


def Custom(*inputs, **kwargs):
    """nd.Custom(*data, op_type='my_op', **op_kwargs) — eager custom op
    invocation (reference MXImperativeInvoke on the 'Custom' op)."""
    op_type = kwargs.pop("op_type", None)
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    if op_type not in _REGISTRY:
        raise MXNetError(
            "custom op type %s is not registered; registered: %s"
            % (op_type, sorted(_REGISTRY)))
    prop = _REGISTRY[op_type](**kwargs)
    op = prop.create_operator(None, [i.shape for i in inputs],
                              [i.dtype for i in inputs])
    fn = _CustomFunction(op, prop, len(prop.list_outputs()))
    return fn(*inputs)
