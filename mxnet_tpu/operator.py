"""Custom Python operators (reference: python/mxnet/operator.py
CustomOp:428 / CustomOpProp:474 / register:694; C++ host
src/operator/custom/custom.cc runs the callbacks on a dedicated
thread).

TPU-native scope: eagerly, custom ops run on the host between XLA
computations (the autograd tape records their backward like any other
op — nd.Custom). SYMBOLICALLY (sym.Custom, hybridize, executors) the
user callbacks are staged into the compiled program via
jax.pure_callback: the XLA program calls back onto the HOST at the
node's position — the same architecture as the reference's dedicated
custom-op thread (custom.cc), with the same costs (breaks fusion
around the node, host round-trip per call). Inside the callback the
user's NDArray code runs on the CPU backend, never re-entering the
device that is executing the outer program."""

from .base import MXNetError
from . import autograd

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered",
           "Custom"]

_REGISTRY = {}


class CustomOp(object):
    """Base class for custom eager operators."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Assign src to dst honouring the grad req."""
        if req in ("null", None):
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError("unknown req %s" % req)


class CustomOpProp(object):
    """Describes a custom op: arguments, outputs, shapes, types."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), \
            [in_shape[0]] * len(self.list_auxiliary_states())

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Decorator registering a CustomOpProp subclass under a name."""

    def do_register(prop_cls):
        assert issubclass(prop_cls, CustomOpProp), \
            "can only register subclass of CustomOpProp"
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered():
    return dict(_REGISTRY)


class _CustomFunction(autograd.Function):
    def __init__(self, op, prop, num_outputs):
        super(_CustomFunction, self).__init__()
        self._op = op
        self._prop = prop
        self._num_outputs = num_outputs
        self._in_data = None
        self._out_data = None

    def __call__(self, *inputs):
        # capture training state BEFORE Function.__call__ wraps forward in
        # autograd.pause() (which would reset the mode). Train MODE, not
        # recording: `train_mode()` without record() must still run the
        # op in training behavior, and `record(train_mode=False)` must
        # not (reference Imperative::is_training(), custom.cc) — keeps
        # eager consistent with the per-mode compiled graphs.
        self._is_train = autograd.is_training()
        return super(_CustomFunction, self).__call__(*inputs)

    def forward(self, *inputs):
        from . import ndarray as nd
        out_shapes = self._prop.infer_shape(
            [i.shape for i in inputs])[1]
        in_types = [i.dtype for i in inputs]
        out_types = self._prop.infer_type(in_types)[1]
        outputs = [nd.zeros(s, dtype=t)
                   for s, t in zip(out_shapes, out_types)]
        self._op.forward(is_train=self._is_train,
                         req=["write"] * len(outputs),
                         in_data=list(inputs), out_data=outputs, aux=[])
        self._in_data = list(inputs)
        self._out_data = outputs
        return outputs if len(outputs) > 1 else outputs[0]

    def backward(self, *out_grads):
        from . import ndarray as nd
        in_grads = [nd.zeros(i.shape, dtype=i.dtype)
                    for i in self._in_data]
        self._op.backward(req=["write"] * len(in_grads),
                          out_grad=list(out_grads),
                          in_data=self._in_data,
                          out_data=self._out_data,
                          in_grad=in_grads, aux=[])
        return in_grads if len(in_grads) > 1 else in_grads[0]


def _instantiate(op_type, attrs):
    if op_type not in _REGISTRY:
        raise MXNetError(
            "custom op type %s is not registered; registered: %s"
            % (op_type, sorted(_REGISTRY)))
    return _REGISTRY[op_type](**attrs)


def _num_outputs_from_attrs(attrs):
    """Arity resolver for the symbol layer (symbol._NUM_OUTPUTS_FROM_ATTRS)."""
    a = {k: v for k, v in attrs.items()
         if not k.startswith("__") and k != "op_type"}
    return len(_instantiate(attrs["op_type"], a).list_outputs())


def _register_symbolic():
    """Register the graph-level `Custom` op: user callbacks staged into
    compiled programs through jax.pure_callback (+ custom_vjp for the
    user-defined backward)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from . import ops as _ops

    def custom_fn(*datas, op_type=None, is_train=False, **attrs):
        # is_train is injected by the executor/CachedOp per traced mode
        # (build_graph_fn is traced separately for train and inference, so
        # each compiled program stages a callback with the right mode —
        # reference passes ctx.is_train into CustomOperator::Forward,
        # src/operator/custom/custom.cc).
        is_train = bool(is_train)
        prop = _instantiate(op_type, attrs)
        in_shapes = [tuple(d.shape) for d in datas]
        in_dtypes = [np.dtype(d.dtype) for d in datas]
        out_shapes = [tuple(s) for s in prop.infer_shape(
            [list(s) for s in in_shapes])[1]]
        out_dtypes = [np.dtype(t) for t in prop.infer_type(in_dtypes)[1]]
        op = prop.create_operator(None, in_shapes, in_dtypes)
        n_out = len(out_shapes)
        out_struct = tuple(jax.ShapeDtypeStruct(s, t)
                           for s, t in zip(out_shapes, out_dtypes))
        in_struct = tuple(jax.ShapeDtypeStruct(s, t)
                          for s, t in zip(in_shapes, in_dtypes))

        def _to_nd(arrays):
            from . import ndarray as nd
            from .context import Context
            cpu = Context("cpu", 0)
            return [nd.array(np.asarray(a), ctx=cpu) for a in arrays]

        def _host_forward(*xs):
            from . import ndarray as nd
            ins = _to_nd(xs)
            outs = [nd.zeros(s, dtype=t.name, ctx=ins[0].context
                             if ins else None)
                    for s, t in zip(out_shapes, out_dtypes)]
            op.forward(is_train=is_train, req=["write"] * n_out,
                       in_data=ins, out_data=outs, aux=[])
            return tuple(np.asarray(o.asnumpy(), dtype=t)
                         for o, t in zip(outs, out_dtypes))

        def _host_backward(*args):
            from . import ndarray as nd
            gs = _to_nd(args[:n_out])
            ins = _to_nd(args[n_out:n_out + len(datas)])
            outs = _to_nd(args[n_out + len(datas):])
            grads = [nd.zeros(s, dtype=t.name)
                     for s, t in zip(in_shapes, in_dtypes)]
            op.backward(req=["write"] * len(grads), out_grad=gs,
                        in_data=ins, out_data=outs, in_grad=grads,
                        aux=[])
            return tuple(np.asarray(g.asnumpy(), dtype=t)
                         for g, t in zip(grads, in_dtypes))

        @jax.custom_vjp
        def run(*xs):
            return jax.pure_callback(_host_forward, out_struct, *xs,
                                     vmap_method="sequential")

        def run_fwd(*xs):
            outs = jax.pure_callback(_host_forward, out_struct, *xs,
                                     vmap_method="sequential")
            return outs, (xs, outs)

        def run_bwd(res, cts):
            xs, outs = res
            grads = jax.pure_callback(_host_backward, in_struct,
                                      *(tuple(cts) + tuple(xs)
                                        + tuple(outs)),
                                      vmap_method="sequential")
            return tuple(grads)

        run.defvjp(run_fwd, run_bwd)
        result = run(*datas)
        return list(result) if n_out > 1 else result[0]

    _ops.register(name="Custom", differentiable=True,
                  num_outputs="n")(custom_fn)
    # late registration: the sym namespace was synthesized before this
    # module imported — attach the symbol function and arity resolver
    from . import symbol as _symbol
    _symbol.__dict__.setdefault(
        "Custom", _symbol._make_sym_func("Custom"))
    _symbol._VARIADIC_ARITY["Custom"] = _num_outputs_from_attrs


_register_symbolic()


def Custom(*inputs, **kwargs):
    """nd.Custom(*data, op_type='my_op', **op_kwargs) — eager custom op
    invocation (reference MXImperativeInvoke on the 'Custom' op)."""
    op_type = kwargs.pop("op_type", None)
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    if op_type not in _REGISTRY:
        raise MXNetError(
            "custom op type %s is not registered; registered: %s"
            % (op_type, sorted(_REGISTRY)))
    prop = _REGISTRY[op_type](**kwargs)
    op = prop.create_operator(None, [i.shape for i in inputs],
                              [i.dtype for i in inputs])
    fn = _CustomFunction(op, prop, len(prop.list_outputs()))
    return fn(*inputs)
