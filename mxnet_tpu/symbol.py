"""Symbol — the declarative graph API.

Reference: python/mxnet/symbol/symbol.py (compose/infer/bind) over nnvm
graphs (3rdparty/tvm/nnvm) with MXNet-side passes (src/nnvm/). TPU-native
design: a Symbol is a lightweight Python DAG over the op registry; binding
lowers the whole graph to ONE jit-compiled XLA computation (see
executor.py) instead of per-node engine pushes — the graph "passes"
(gradient, memory planning, fusion) are XLA's job.

JSON serialization follows the reference node-list layout
(symbol.py:1331 tojson) so models survive save/load round-trips.
"""

import json

import numpy as np

import jax
import jax.numpy as jnp

from . import ops
from .base import MXNetError

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json", "zeros",
           "ones"]

# Ops whose trailing signature params are tensor inputs (not attrs); needed
# for symbolic composition where inputs must be identified statically
# (the reference encodes this in each op's FListInputNames).
OP_INPUTS = {
    "Convolution": ("data", "weight", "bias"),
    "Deconvolution": ("data", "weight", "bias"),
    "FullyConnected": ("data", "weight", "bias"),
    "BatchNorm": ("data", "gamma", "beta", "moving_mean", "moving_var"),
    "_contrib_SyncBatchNorm": ("data", "gamma", "beta", "moving_mean",
                               "moving_var"),
    "LayerNorm": ("data", "gamma", "beta"),
    "GroupNorm": ("data", "gamma", "beta"),
    "InstanceNorm": ("data", "gamma", "beta"),
    "Embedding": ("data", "weight"),
    "RNN": ("data", "parameters", "state", "state_cell"),
    "SoftmaxOutput": ("data", "label"),
    "Softmax": ("data", "label"),
    "softmax_cross_entropy": ("data", "label"),
    "CTCLoss": ("data", "label", "data_lengths", "label_lengths"),
    "ctc_loss": ("data", "label", "data_lengths", "label_lengths"),
    "LeakyReLU": ("data", "gamma"),
    "SequenceMask": ("data", "sequence_length"),
    "SequenceLast": ("data", "sequence_length"),
    "SequenceReverse": ("data", "sequence_length"),
    "BilinearSampler": ("data", "grid"),
    "SpatialTransformer": ("data", "loc"),
    "ROIPooling": ("data", "rois"),
    "_contrib_ROIAlign": ("data", "rois"),
    "where": ("condition", "x", "y"),
    "dot": ("lhs", "rhs"),
    "batch_dot": ("lhs", "rhs"),
}

# Aux states: inputs updated by the op during training rather than learned
# by gradient (reference: MutableInput lists; BatchNorm moving stats).
OP_AUX = {"BatchNorm": ("moving_mean", "moving_var"),
          "_contrib_SyncBatchNorm": ("moving_mean", "moving_var")}
# default initializer registry names for auto-created aux states
_AUX_DEFAULT_INIT = {"moving_mean": "zeros", "moving_var": "ones"}


def _rnn_param_init(attrs):
    """__init__ attr for the RNN packed-parameter var: the FusedRNN
    initializer needs the cell geometry to lay out gate weights/biases
    (the reference stamps the same via rnn_cell.FusedRNNCell)."""
    return json.dumps(["fusedrnn", {
        "init": None,
        "num_hidden": attrs.get("state_size", 1),
        "num_layers": attrs.get("num_layers", 1),
        "mode": attrs.get("mode", "lstm"),
        "bidirectional": bool(attrs.get("bidirectional", False)),
    }])


# per-(op, param) default __init__ stamps for auto-created variables
_PARAM_DEFAULT_INIT = {("RNN", "parameters"): _rnn_param_init}

# Loss heads whose missing `label` input is auto-created as `{name}_label`
# (the reference's ListArguments auto-var rule that makes `softmax_label`
# appear in list_arguments()). Value = label-shape rule from data shape.
LABEL_SHAPE_RULES = {
    "SoftmaxOutput": lambda ds, at: ds[:1] if not at.get("multi_output")
    else (ds[0],) + tuple(ds[2:]),
    "Softmax": lambda ds, at: ds[:1],
    "softmax_cross_entropy": lambda ds, at: ds[:1],
    "LinearRegressionOutput": lambda ds, at: ds,
    "MAERegressionOutput": lambda ds, at: ds,
    "LogisticRegressionOutput": lambda ds, at: ds,
}

# Params auto-created as trainable variables when omitted at composition
# time, and their deferred-shape rule given the first input's shape.
_NORM_PARAM = lambda data_shape, attrs, axis=1: (data_shape[attrs.get("axis", axis) % len(data_shape)],)


def _conv_w(data_shape, attrs):
    kernel = attrs.get("kernel", ())
    nf = attrs.get("num_filter", 1)
    ng = attrs.get("num_group", 1)
    return (nf, data_shape[1] // ng) + tuple(kernel)


def _deconv_w(data_shape, attrs):
    kernel = attrs.get("kernel", ())
    nf = attrs.get("num_filter", 1)
    ng = attrs.get("num_group", 1)
    return (data_shape[1], nf // ng) + tuple(kernel)


def _fc_w(data_shape, attrs):
    nh = attrs.get("num_hidden", 1)
    if attrs.get("flatten", True):
        in_units = int(np.prod(data_shape[1:]))
    else:
        in_units = data_shape[-1]
    return (nh, in_units)


def _rnn_params(data_shape, attrs):
    from .ops.nn import rnn_param_size
    return (rnn_param_size(attrs.get("mode", "lstm"), attrs.get("num_layers", 1),
                           data_shape[2], attrs.get("state_size", 1),
                           attrs.get("bidirectional", False)),)


PARAM_SHAPE_RULES = {
    "Convolution": {"weight": _conv_w,
                    "bias": lambda ds, at: (at.get("num_filter", 1),)},
    "Deconvolution": {"weight": _deconv_w,
                      "bias": lambda ds, at: (at.get("num_filter", 1),)},
    "FullyConnected": {"weight": _fc_w,
                       "bias": lambda ds, at: (at.get("num_hidden", 1),)},
    "BatchNorm": {k: _NORM_PARAM for k in
                  ("gamma", "beta", "moving_mean", "moving_var")},
    "_contrib_SyncBatchNorm": {k: _NORM_PARAM for k in
                               ("gamma", "beta", "moving_mean",
                                "moving_var")},
    "LayerNorm": {"gamma": lambda ds, at: (ds[at.get("axis", -1) % len(ds)],),
                  "beta": lambda ds, at: (ds[at.get("axis", -1) % len(ds)],)},
    "GroupNorm": {"gamma": _NORM_PARAM, "beta": _NORM_PARAM},
    "InstanceNorm": {"gamma": _NORM_PARAM, "beta": _NORM_PARAM},
    "RNN": {"parameters": _rnn_params},
    "LeakyReLU": {"gamma": lambda ds, at: (ds[1] if len(ds) > 1 else 1,)},
    "Embedding": {"weight": lambda ds, at: (at.get("input_dim", 1),
                                            at.get("output_dim", 1))},
}


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs")

    def __init__(self, op, name, attrs, inputs):
        self.op = op          # op name string; "null" for variables
        self.name = name
        self.attrs = attrs    # static attrs (variables store __shape__ etc.)
        self.inputs = inputs  # list of (Symbol(single-node), out_index)

    def is_var(self):
        return self.op == "null"


_name_counter = {}


def _auto_name(op_name, name=None):
    """Route op names through the active NameManager: auto-generated
    names draw from the scope's counter; explicit names pick up the
    scope prefix (so layer-internal fixed names like 'fwd' stay unique
    across sibling blocks)."""
    from .name import NameManager
    base = op_name.lower().lstrip("_")
    return NameManager.current().get(name, base)


class Symbol:
    """Symbolic multi-output expression (python/mxnet/symbol/symbol.py:61)."""

    def __init__(self, nodes, outputs):
        # nodes: topo-ordered list of _Node; outputs: list of (node_idx, out_idx)
        self._nodes = nodes
        self._outputs = outputs

    # ------------------------------------------------------- structure --
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._nodes[self._outputs[0][0]].name
        return None

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            index = names.index(index)
        return Symbol(self._nodes, [self._outputs[index]])

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def _active_nodes(self):
        """Topo-ordered ancestor set of this symbol's outputs (a Symbol can
        share a larger node list, e.g. after get_internals slicing)."""
        active = set()
        stack = [self._nodes[ni] for ni, _ in self._outputs]
        while stack:
            n = stack.pop()
            if id(n) in active:
                continue
            active.add(id(n))
            for s, _ in n.inputs:
                stack.append(s._nodes[s._outputs[0][0]])
        return [n for n in self._nodes if id(n) in active]

    def list_arguments(self):
        seen, out = set(), []
        for n in self._active_nodes():
            if n.is_var() and not n.attrs.get("__aux__") and n.name not in seen:
                seen.add(n.name)
                out.append(n.name)
        return out

    def list_auxiliary_states(self):
        seen, out = set(), []
        for n in self._active_nodes():
            if n.is_var() and n.attrs.get("__aux__") and n.name not in seen:
                seen.add(n.name)
                out.append(n.name)
        return out

    def list_outputs(self):
        out = []
        for ni, oi in self._outputs:
            node = self._nodes[ni]
            if node.is_var():
                out.append(node.name)
                continue
            op = ops.get(node.op)
            if op.num_outputs == 1 or \
                    node.op in ("BatchNorm", "_contrib_SyncBatchNorm"):
                suffix = "_output"
            else:
                suffix = "_output%d" % oi
            out.append(node.name + suffix)
        return out

    def list_inputs(self):
        return self.list_arguments() + self.list_auxiliary_states()

    def get_internals(self):
        outs = []
        for i, n in enumerate(self._nodes):
            if n.is_var():
                outs.append((i, 0))
            else:
                nout = _node_num_outputs(n)
                outs.extend((i, k) for k in range(nout))
        return Symbol(self._nodes, outs)

    def get_children(self):
        ni, _ = self._outputs[0]
        node = self._nodes[ni]
        if not node.inputs:
            return None
        return Symbol(self._nodes, [(_find_index(self._nodes, s._nodes[s._outputs[0][0]]), oi)
                                    for s, oi in node.inputs])

    def attr(self, key):
        ni, _ = self._outputs[0]
        return self._nodes[ni].attrs.get(key)

    def attr_dict(self):
        return {n.name: dict(n.attrs) for n in self._nodes if n.attrs}

    def _set_attr(self, **kwargs):
        ni, _ = self._outputs[0]
        self._nodes[ni].attrs.update(kwargs)

    # ------------------------------------------------------ compose ops --
    def __call__(self, *args, **kwargs):
        raise MXNetError("Symbol composition via __call__ is not supported; "
                         "pass symbols as op arguments")

    def __add__(self, other):
        return _binary_sym("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return _binary_sym("broadcast_add", "_plus_scalar", self, other)

    def __sub__(self, other):
        return _binary_sym("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _scalar_sym("_rminus_scalar", self, other)

    def __mul__(self, other):
        return _binary_sym("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return _binary_sym("broadcast_mul", "_mul_scalar", self, other)

    def __truediv__(self, other):
        return _binary_sym("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _scalar_sym("_rdiv_scalar", self, other)

    def __pow__(self, other):
        return _binary_sym("broadcast_power", "_power_scalar", self, other)

    def __neg__(self):
        return _unary_sym("negative", self)

    # rich comparisons compose broadcast/scalar compare ops (reference
    # symbol.py __gt__ etc.); note __eq__/__ne__ build symbols, so Symbol
    # is identity-hashed like the reference
    def __eq__(self, other):
        return _binary_sym("broadcast_equal", "_equal_scalar", self, other)

    def __ne__(self, other):
        return _binary_sym("broadcast_not_equal", "_not_equal_scalar",
                           self, other)

    def __gt__(self, other):
        return _binary_sym("broadcast_greater", "_greater_scalar",
                           self, other)

    def __ge__(self, other):
        return _binary_sym("broadcast_greater_equal",
                           "_greater_equal_scalar", self, other)

    def __lt__(self, other):
        return _binary_sym("broadcast_lesser", "_lesser_scalar",
                           self, other)

    def __le__(self, other):
        return _binary_sym("broadcast_lesser_equal",
                           "_lesser_equal_scalar", self, other)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        name = self.name
        return "<Symbol %s>" % (name if name else "Grouped")

    def reshape(self, shape, reverse=False):
        return _compose("Reshape", [self], {"shape": tuple(shape),
                                            "reverse": reverse}, None)

    def astype(self, dtype):
        return _compose("Cast", [self], {"dtype": str(np.dtype(dtype))}, None)

    # -------------------------------------------------------- inference --
    def infer_shape(self, *args, **kwargs):
        """Forward shape inference incl. deferred parameter shapes
        (reference: infer_graph_attr_pass.cc; here jax.eval_shape per node
        + PARAM_SHAPE_RULES for auto-created parameter variables)."""
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        known = {}
        if args:
            for name, shp in zip(self.list_arguments(), args):
                if shp is not None:
                    known[name] = tuple(shp)
        known.update({k: tuple(v) for k, v in kwargs.items()})
        shapes, dtypes = _infer_graph(self._active_nodes(), known, {},
                                      partial=partial)
        arg_shapes = [shapes.get(n) for n in self.list_arguments()]
        aux_shapes = [shapes.get(n) for n in self.list_auxiliary_states()]
        out_shapes = [shapes.get(_out_key(self._nodes, ni, oi))
                      for ni, oi in self._outputs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        known = {}
        if args:
            for name, dt in zip(self.list_arguments(), args):
                if dt is not None:
                    known[name] = np.dtype(dt)
        known.update({k: np.dtype(v) for k, v in kwargs.items()})
        shapes, dtypes = _infer_graph(self._active_nodes(), {}, known,
                                      partial=True)
        args_t = [dtypes.get(n) for n in self.list_arguments()]
        aux_t = [dtypes.get(n) for n in self.list_auxiliary_states()]
        out_t = [dtypes.get(_out_key(self._nodes, ni, oi))
                 for ni, oi in self._outputs]
        return args_t, out_t, aux_t

    # ---------------------------------------------------------- binding --
    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        from .executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx)

    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    group2ctx=None, shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, **kwargs):
        """symbol.py:1441 — infer shapes, allocate arg/grad/aux arrays, bind."""
        from . import ndarray as nd
        from .executor import Executor
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        type_dict = dict(type_dict or {})
        # variables may pin their dtype via the __dtype__ attr
        # (e.g. int8 quantized weights)
        for node in self._active_nodes():
            if node.is_var() and "__dtype__" in node.attrs:
                type_dict.setdefault(node.name, node.attrs["__dtype__"])
        args = {}
        for name, shp in zip(arg_names, arg_shapes):
            if shp is None:
                raise MXNetError("cannot infer shape for argument %s" % name)
            args[name] = nd.zeros(shp, ctx=ctx,
                                  dtype=type_dict.get(name, "float32"))
        args_grad = None
        if grad_req != "null":
            args_grad = {name: nd.zeros(a.shape, ctx=ctx, dtype=a.dtype)
                         for name, a in args.items()}
        aux = {name: nd.zeros(shp, ctx=ctx)
               for name, shp in zip(aux_names, aux_shapes)}
        return Executor(self, ctx, args, args_grad, grad_req, aux,
                        group2ctx=group2ctx)

    def eval(self, ctx=None, **kwargs):
        from .context import current_context
        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # ------------------------------------------------------------ io ----
    def tojson(self):
        """symbol.py:1331 — reference-layout JSON node list. Subgraph-
        valued attrs (control-flow ops) serialize into the node's
        "subgraphs" list, as the reference format does."""
        node_index = {id(n): i for i, n in enumerate(self._nodes)}
        nodes = []
        for n in self._nodes:
            attrs = {}
            subgraphs = []
            for k, v in n.attrs.items():
                if isinstance(v, Symbol):
                    attrs[k] = "__subgraph__:%d" % len(subgraphs)
                    subgraphs.append(json.loads(v.tojson()))
                else:
                    attrs[k] = str(v)
            entry = {
                "op": n.op,
                "name": n.name,
                "attrs": attrs,
                "inputs": [[node_index[id(s._nodes[s._outputs[0][0]])], oi, 0]
                           for s, oi in n.inputs],
            }
            if subgraphs:
                entry["subgraphs"] = subgraphs
            nodes.append(entry)
        heads = [[ni, oi, 0] for ni, oi in self._outputs]
        arg_nodes = [i for i, n in enumerate(self._nodes) if n.is_var()]
        return json.dumps({"nodes": nodes, "arg_nodes": arg_nodes,
                           "heads": heads,
                           "attrs": {"mxnet_tpu_version": ["str", "0.1.0"]}},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # gradient helper (MXGradient pass analogue) — symbolic grad symbols
    # are not materialized as Symbols; Executor computes grads via jax.vjp.


def _node_num_outputs(node):
    if node.is_var():
        return 1
    if "__num_outputs__" in node.attrs:
        # per-node arity (control-flow subgraph ops: outputs depend on
        # the traced body, not the op class)
        return int(node.attrs["__num_outputs__"])
    op = ops.get(node.op)
    if node.op in ("BatchNorm", "_contrib_SyncBatchNorm"):
        return 1  # mean/var are internal plumbing, not user outputs
    if op.num_outputs == "n":
        resolver = _VARIADIC_ARITY.get(node.op)
        if resolver is not None:
            return resolver(node.attrs)
        return 1
    return op.num_outputs


def _seed_count(attrs, csr_inputs):
    """Graph samplers: outputs = one vertex vector per seed array."""
    return max(int(attrs.get("num_args", csr_inputs + 1)) - csr_inputs, 1)


# arity of num_outputs=="n" ops as a function of their static attrs
# (the symbolic analogue of the reference's set_num_outputs lambdas)
_VARIADIC_ARITY = {
    "SliceChannel": lambda a: int(a.get("num_outputs", 1)),
    "split": lambda a: int(a.get("num_outputs", 1)),
    "topk": lambda a: 2 if a.get("ret_typ") == "both" else 1,
    "RNN": lambda a: 3 if a.get("mode", "lstm") == "lstm" else 2,
    "_split_v2": lambda a: (int(a["sections"]) if int(a.get("sections", 0)) > 0
                            else len(tuple(a.get("indices", ())))),
    "amp_multicast": lambda a: int(a.get("num_outputs", 1)),
    "multi_sgd_update": lambda a: int(a.get("num_weights", 1)),
    "multi_sgd_mom_update": lambda a: int(a.get("num_weights", 1)),
    "preloaded_multi_sgd_update": lambda a: int(a.get("num_weights", 1)),
    "preloaded_multi_sgd_mom_update": lambda a: int(a.get("num_weights", 1)),
    "multi_mp_sgd_update": lambda a: int(a.get("num_weights", 1)),
    "multi_mp_sgd_mom_update": lambda a: int(a.get("num_weights", 1)),
    "_contrib_dgl_csr_neighbor_uniform_sample": lambda a: _seed_count(a, 2),
    "_contrib_dgl_csr_neighbor_non_uniform_sample":
        lambda a: _seed_count(a, 3),
    "_contrib_dgl_subgraph": lambda a: 2 * _seed_count(a, 2),
    "_contrib_dgl_graph_compact": lambda a: 3 * max(
        int(a.get("num_args", 4)) // 4, 1),
}


def _out_key(nodes, ni, oi):
    return "%s#%d" % (nodes[ni].name, oi)


def _find_index(nodes, node):
    for i, n in enumerate(nodes):
        if n is node:
            return i
    raise KeyError


def _merge_nodes(syms):
    """Union the node lists of several symbols preserving topo order."""
    merged = []
    seen = set()
    def visit(nodes):
        for n in nodes:
            if id(n) not in seen:
                seen.add(id(n))
                merged.append(n)
    for s in syms:
        visit(s._nodes)
    return merged


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """sym.var / sym.Variable (symbol.py:2516)."""
    from .attribute import AttrScope
    scoped = AttrScope.current().get(None)
    attrs = {("__%s__" % k if not k.startswith("__") else k): v
             for k, v in scoped.items()} if scoped else {}
    attrs.update(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(np.dtype(dtype))
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    attrs.update(kwargs)
    node = _Node("null", name, attrs, [])
    return Symbol([node], [(0, 0)])


Variable = var


def Group(symbols):
    nodes = _merge_nodes(symbols)
    outputs = []
    for s in symbols:
        for ni, oi in s._outputs:
            outputs.append((_find_index(nodes, s._nodes[ni]), oi))
    return Symbol(nodes, outputs)


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    syms = []
    for nd_ in data["nodes"]:
        inputs = [(syms[i], oi) for i, oi, _ in nd_["inputs"]]
        attrs = {}
        for k, v in nd_.get("attrs", {}).items():
            if isinstance(v, str) and v.startswith("__subgraph__:"):
                sg = nd_["subgraphs"][int(v.split(":", 1)[1])]
                attrs[k] = load_json(json.dumps(sg))
            else:
                attrs[k] = _parse_attr(v)
        node = _Node(nd_["op"], nd_["name"], attrs, inputs)
        nodes.append(node)
        syms.append(Symbol(nodes[:], [(len(nodes) - 1, 0)]))
    outputs = [(ni, oi) for ni, oi, _ in data["heads"]]
    return Symbol(nodes, outputs)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def _parse_attr(v):
    if not isinstance(v, str):
        return v
    s = v.strip()
    if s in ("True", "true"):
        return True
    if s in ("False", "false"):
        return False
    if s.startswith("(") or s.startswith("["):
        items = [x.strip() for x in s.strip("()[]").split(",") if x.strip()]
        out = []
        for x in items:
            if x.lstrip("-").isdigit():
                out.append(int(x))
            else:
                try:
                    out.append(float(x))
                except ValueError:
                    out.append(x.strip("'\""))
        return tuple(out)
    for cast in (int, float):
        try:
            return cast(s)
        except ValueError:
            continue
    return v


# ---------------------------------------------------------- composition --
def _compose(op_name, input_syms, attrs, name):
    """Create a node applying `op_name` to input symbols. `name`, when
    given, is already scope-resolved by the caller (func/_auto_name) —
    resolving again here would apply the active Prefix twice."""
    if not name:
        name = _auto_name(op_name)
    nodes = _merge_nodes(input_syms)
    node = _Node(op_name, name, attrs,
                 [(s, s._outputs[0][1]) for s in input_syms])
    nodes.append(node)
    nout = _node_num_outputs(node)
    return Symbol(nodes, [(len(nodes) - 1, k) for k in range(nout)]) \
        if nout > 1 else Symbol(nodes, [(len(nodes) - 1, 0)])


def _binary_sym(op, scalar_op, lhs, rhs):
    if isinstance(rhs, Symbol):
        return _compose(op, [lhs, rhs], {}, None)
    return _compose(scalar_op, [lhs], {"scalar": float(rhs)}, None)


def _scalar_sym(op, data, scalar):
    return _compose(op, [data], {"scalar": float(scalar)}, None)


def _unary_sym(op, data):
    return _compose(op, [data], {}, None)


def _make_sym_func(op_name):
    import inspect as _inspect
    op = ops.get(op_name)
    sig = ops.op_signature(op_name)
    has_varargs = any(p.kind == _inspect.Parameter.VAR_POSITIONAL
                      for p in sig.parameters.values())
    declared_inputs = OP_INPUTS.get(op_name)
    if declared_inputs is None:
        declared_inputs = tuple(
            p.name for p in sig.parameters.values()
            if p.default is _inspect.Parameter.empty
            and p.kind == _inspect.Parameter.POSITIONAL_OR_KEYWORD)

    def func(*args, name=None, attr=None, **kwargs):
        input_syms = []
        input_names = []
        attrs = {}
        pos_inputs = list(args)
        if has_varargs:
            flat = []
            for a in pos_inputs:
                flat.extend(a) if isinstance(a, (list, tuple)) else flat.append(a)
            input_syms = [a for a in flat if isinstance(a, Symbol)]
            input_names = [None] * len(input_syms)
        else:
            # bind positionals to signature parameters in order: Symbols are
            # inputs, everything else is a static attr of that parameter
            pnames = [p.name for p in sig.parameters.values()
                      if p.kind == _inspect.Parameter.POSITIONAL_OR_KEYWORD]
            for a, pname in zip(pos_inputs, pnames):
                if isinstance(a, Symbol):
                    input_syms.append(a)
                    input_names.append(pname)
                elif a is not None:
                    attrs[pname] = a
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                input_syms.append(v)
                input_names.append(k)
            elif v is not None:
                attrs[k] = v
        nm = _auto_name(op_name, name)

        # auto-create missing parameter variables (MXNet composition rule)
        if not has_varargs and op_name in PARAM_SHAPE_RULES:
            have = set(n for n in input_names if n)
            aux_set = set(OP_AUX.get(op_name, ()))
            for pname in declared_inputs:
                if pname in have or pname == declared_inputs[0]:
                    continue
                if _param_unused(op_name, pname, attrs):
                    continue
                vattrs = {"__aux__": True} if pname in aux_set else {}
                # ops declare default inits for their aux states (the
                # reference stamps __init__ on aux vars at composition,
                # batch_norm.cc); initializers route on this attr
                default_init = _AUX_DEFAULT_INIT.get(pname)
                if pname in aux_set and default_init:
                    vattrs["__init__"] = default_init
                param_init = _PARAM_DEFAULT_INIT.get((op_name, pname))
                if param_init is not None:
                    vattrs["__init__"] = param_init(attrs)
                v = var("%s_%s" % (nm, pname), attr=vattrs)
                input_syms.append(v)
                input_names.append(pname)
        # auto-create the label variable for loss heads ({name}_label)
        if not has_varargs and op_name in LABEL_SHAPE_RULES \
                and "label" not in set(n for n in input_names if n):
            v = var("%s_label" % nm)
            input_syms.append(v)
            input_names.append("label")
        # order inputs by declared order when names are known
        if input_names and all(n is not None for n in input_names) and not has_varargs:
            order = {n: i for i, n in enumerate(declared_inputs)}
            zipped = sorted(zip(input_names, input_syms),
                            key=lambda t: order.get(t[0], 99))
            input_syms = [s for _, s in zipped]
            input_names = [n for n, _ in zipped]
        # mark explicitly-passed variables bound to aux inputs (e.g. gluon
        # passing running_mean into BatchNorm's moving_mean slot) as aux
        aux_inputs = set(OP_AUX.get(op_name, ()))
        if aux_inputs and not has_varargs:
            for n, s in zip(input_names, input_syms):
                if n in aux_inputs:
                    node = s._nodes[s._outputs[0][0]]
                    if node.is_var():
                        node.attrs["__aux__"] = True
        attrs["__input_names__"] = tuple(n or "arg%d" % i
                                         for i, n in enumerate(input_names))
        from .attribute import AttrScope
        scoped = AttrScope.current().get(attr)
        if scoped:
            attrs.update(("__%s__" % k if not k.startswith("__") else k, v)
                         for k, v in scoped.items())
        return _compose(op_name, input_syms, attrs, nm)

    func.__name__ = op_name
    func.__doc__ = (op.fn.__doc__ or "") + "\n\n(symbolic version)"
    return func


def _param_unused(op_name, pname, attrs):
    if pname == "bias" and attrs.get("no_bias"):
        return True
    if pname in ("state", "state_cell"):
        # the RNN op synthesizes zero initial states when omitted; don't
        # auto-create bindable begin-state variables
        return True
    if pname in ("sequence_length", "data_lengths", "label_lengths") \
            and not attrs.get("use_sequence_length"):
        return True
    if op_name == "LeakyReLU" and pname == "gamma" \
            and attrs.get("act_type", "leaky") != "prelu":
        return True
    if pname == "label":
        return False
    return False


_g = globals()
for _opname in ops.list_ops():
    if _opname not in _g:
        _g[_opname] = _make_sym_func(_opname)
for _alias in list(ops._ALIAS):
    if _alias not in _g:
        _g[_alias] = _make_sym_func(_alias)


def zeros(shape, dtype="float32", **kwargs):
    return _g["_zeros"](shape=shape, dtype=dtype, **kwargs)


def ones(shape, dtype="float32", **kwargs):
    return _g["_ones"](shape=shape, dtype=dtype, **kwargs)


class _SymContribNamespace:
    def __getattr__(self, item):
        if item in ("foreach", "while_loop", "cond"):
            from . import control_flow
            return getattr(control_flow, "sym_" + item)
        full = "_contrib_" + item
        if ops.exists(full):
            return _g.get(full) or _make_sym_func(full)
        if ops.exists(item):
            return _g.get(item) or _make_sym_func(item)
        raise AttributeError(item)


contrib = _SymContribNamespace()


class _SymImageNamespace:
    def __getattr__(self, item):
        full = "_image_" + item
        if ops.exists(full):
            return _g.get(full) or _make_sym_func(full)
        raise AttributeError(item)


image = _SymImageNamespace()


class _SymLinalgNamespace:
    def __getattr__(self, item):
        full = "linalg_" + item
        if ops.exists(full):
            return _g.get(full) or _make_sym_func(full)
        raise AttributeError(item)


linalg = _SymLinalgNamespace()


class _SymRandomNamespace:
    """sym.random.* (python/mxnet/symbol/random.py) — scalar-parameter
    draws resolve to the `_random_*` ops, symbol parameters to the
    `_sample_*` ops, same split as the nd namespace."""

    def __getattr__(self, item):
        scalar_op = "_random_" + item
        tensor_op = "sample_" + item
        if not (ops.exists(scalar_op) or ops.exists(tensor_op)):
            raise AttributeError(item)

        def f(*args, **kwargs):
            if any(isinstance(a, Symbol) for a in args) or \
                    any(isinstance(v, Symbol) for v in kwargs.values()):
                fn = _g.get(tensor_op) or _make_sym_func(tensor_op)
            else:
                fn = _g.get(scalar_op) or _make_sym_func(scalar_op)
            return fn(*args, **kwargs)
        f.__name__ = item
        return f


random = _SymRandomNamespace()


# ----------------------------------------------------- graph inference --
def _infer_graph(nodes, known_shapes, known_dtypes, partial=False):
    """Walk the graph computing per-node output ShapeDtype via
    jax.eval_shape; fill missing variable shapes from PARAM_SHAPE_RULES."""
    from .executor import node_eval_fn
    from .observability import recompile as _obs_recompile

    shapes = dict(known_shapes)
    dtypes = dict(known_dtypes)
    results = {}  # node name -> list of ShapeDtypeStruct

    # eval_shape fires per-node jaxpr-trace events; they are shape
    # inference, not executable re-traces — keep them off the recompile
    # detector's steady-state budget (they'd be blamed on whatever jit
    # boundary ran last)
    with _obs_recompile.suppress_events():
        return _infer_graph_impl(nodes, node_eval_fn, shapes, dtypes,
                                 results, partial)


def _infer_graph_impl(nodes, node_eval_fn, shapes, dtypes, results,
                      partial):
    for node in nodes:
        if node.is_var():
            shp = shapes.get(node.name) or node.attrs.get("__shape__")
            dt = dtypes.get(node.name) or np.dtype(
                node.attrs.get("__dtype__", "float32"))
            if shp is not None:
                shapes[node.name] = tuple(shp)
                results[node.name] = [jax.ShapeDtypeStruct(tuple(shp), dt)]
                dtypes[node.name] = np.dtype(dt)
            continue
        # gather input specs, inferring deferred parameter shapes
        in_specs = []
        in_names = node.attrs.get("__input_names__",
                                  tuple("arg%d" % i for i in range(len(node.inputs))))
        data_spec = None
        for (s, oi), pname in zip(node.inputs, in_names):
            src = s._nodes[s._outputs[0][0]]
            srcres = results.get(src.name)
            if srcres is None and src.is_var():
                # try deferred param shape rule
                rule = PARAM_SHAPE_RULES.get(node.op, {}).get(pname)
                if rule is None and pname == "label":
                    rule = LABEL_SHAPE_RULES.get(node.op)
                if rule is not None and data_spec is not None:
                    shp = rule(data_spec.shape, node.attrs)
                    dt = data_spec.dtype
                    results[src.name] = [jax.ShapeDtypeStruct(tuple(shp), dt)]
                    shapes[src.name] = tuple(shp)
                    dtypes[src.name] = np.dtype(dt)
                    srcres = results[src.name]
                elif node.op == "RNN" and pname in ("state", "state_cell") \
                        and data_spec is not None:
                    d = 2 if node.attrs.get("bidirectional") else 1
                    shp = (node.attrs.get("num_layers", 1) * d,
                           data_spec.shape[1], node.attrs.get("state_size", 1))
                    results[src.name] = [jax.ShapeDtypeStruct(shp, data_spec.dtype)]
                    shapes[src.name] = shp
                    dtypes[src.name] = np.dtype(data_spec.dtype)
                    srcres = results[src.name]
            if srcres is None:
                if partial:
                    results[node.name] = None
                    srcres = None
                    break
                raise MXNetError("infer_shape: missing shape for input %s of "
                                 "node %s(%s)" % (src.name, node.op, node.name))
            spec = srcres[oi] if len(srcres) > oi else srcres[0]
            in_specs.append(spec)
            if data_spec is None:
                data_spec = spec
        else:
            fn = node_eval_fn(node, for_inference=True)
            try:
                out = jax.eval_shape(fn, *in_specs)
            except Exception as e:
                if partial:
                    results[node.name] = None
                    continue
                raise MXNetError("infer_shape failed at %s(%s): %s"
                                 % (node.op, node.name, e))
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            results[node.name] = outs
            for k, o in enumerate(outs):
                shapes[_node_out_name(node, k)] = tuple(o.shape)
                dtypes[_node_out_name(node, k)] = np.dtype(o.dtype)
            continue
        # (break path: partial inference, leave unknown)
    return shapes, dtypes


def _node_out_name(node, k):
    return "%s#%d" % (node.name, k)


# ------------------------------------------------- fluent methods -------
# Reference Symbol fluent methods (python/mxnet/symbol/symbol.py):
# s.relu(), s.sum(axis=..), s.slice_axis(...) delegate to the namespace
# functions; NDArray-only operations raise NotImplementedForSymbol.
_SYM_FLUENT = [
    "abs", "arccos", "arccosh", "arcsin", "arcsinh", "arctan", "arctanh",
    "argmax", "argmax_channel", "argmin", "argsort", "broadcast_axes",
    "broadcast_like", "broadcast_to", "cbrt", "ceil", "clip", "cos",
    "cosh", "degrees", "depth_to_space", "diag", "exp", "expand_dims",
    "expm1", "fix", "flatten", "flip", "floor", "log", "log10", "log1p",
    "log2", "log_softmax", "max", "mean", "min", "nanprod", "nansum",
    "norm", "one_hot", "ones_like", "pad", "pick", "prod", "radians",
    "rcbrt", "reciprocal", "relu", "repeat", "reshape_like", "rint",
    "round", "rsqrt", "shape_array", "sigmoid", "sign", "sin", "sinh",
    "size_array", "slice", "slice_axis", "slice_like", "softmax",
    "softmin", "sort", "space_to_depth", "split", "split_v2", "sqrt",
    "square", "squeeze", "sum", "swapaxes", "take", "tan", "tanh",
    "tile", "topk", "transpose", "trunc", "zeros_like",
]


def _make_sym_fluent(name):
    opname = {"flip": "reverse", "split": "SliceChannel",
              "split_v2": "_split_v2", "pad": "Pad",
              "slice": "slice"}.get(name, name)

    def method(self, *args, **kwargs):
        fn = _g.get(opname) or (_make_sym_func(opname)
                                if ops.exists(opname) else None)
        if fn is None:
            raise AttributeError(name)
        return fn(self, *args, **kwargs)
    method.__name__ = name
    method.__doc__ = "Fluent form of sym.%s(self, ...)." % name
    return method


for _name in _SYM_FLUENT:
    if not hasattr(Symbol, _name):
        setattr(Symbol, _name, _make_sym_fluent(_name))


class NotImplementedForSymbol(MXNetError):
    """Raised by NDArray-only methods called on a Symbol (reference
    symbol.py NotImplementedForSymbol)."""

    def __init__(self, function, *_):
        super().__init__("Function %s is not implemented for Symbol and "
                         "only available in NDArray." % function)


def _sym_na(name):
    def method(self, *args, **kwargs):
        raise NotImplementedForSymbol(name)
    method.__name__ = name
    return method


for _name in ("asnumpy", "asscalar", "wait_to_read", "backward",
              "as_in_context", "copy", "detach"):
    if not hasattr(Symbol, _name):
        setattr(Symbol, _name, _sym_na(_name))

# the numpy-flavored symbol API resolves to the same Symbol class here
# (both namespaces dispatch into the one op registry)
Symbol.as_np_ndarray = lambda self: self
Symbol.as_nd_ndarray = lambda self: self


def _sym_list_attr(self, recursive=False):
    """Attributes of this symbol's node (reference list_attr)."""
    ni, _ = self._outputs[0]
    return dict(self._nodes[ni].attrs)


Symbol.list_attr = _sym_list_attr


def _sym_debug_str(self):
    lines = []
    for i, node in enumerate(self._nodes):
        ins = ", ".join(s._nodes[s._outputs[0][0]].name
                        for s, _ in node.inputs) if node.inputs else ""
        lines.append("%3d %-20s %-24s <- %s"
                     % (i, node.op or "Variable", node.name, ins))
    return "\n".join(lines)


Symbol.debug_str = _sym_debug_str


def _sym_infer_type_partial(self, *args, **kwargs):
    """Like infer_type but tolerates unknowns (reference
    infer_type_partial)."""
    try:
        return self.infer_type(*args, **kwargs)
    except Exception:
        n_args = len(self.list_arguments())
        n_aux = len(self.list_auxiliary_states())
        return ([None] * n_args, [None] * len(self._outputs),
                [None] * n_aux)


Symbol.infer_type_partial = _sym_infer_type_partial


def _sym_gradient(self, wrt):
    """Reference Symbol.gradient is unimplemented in MXNet 1.x as well
    (autodiff happens in bind/executor); keep the same contract."""
    raise MXNetError(
        "Symbol.gradient is not supported (same as the reference); "
        "gradients come from Executor.backward / autograd")


Symbol.gradient = _sym_gradient


def _sym_get_backend_symbol(self, backend):
    """Subgraph-backend partitioning (MKLDNN/TensorRT) has no analogue:
    XLA compiles and fuses the whole graph. Returns self so pipelines
    that call it unconditionally keep working."""
    return self


Symbol.get_backend_symbol = _sym_get_backend_symbol
