"""Parameter-server server-role entry (reference:
python/mxnet/kvstore_server.py).

There IS no server role in the TPU build: `dist_tpu_sync` replaces the
ps-lite push/pull+server-ApplyUpdates protocol with a collective
all-reduce in which every process is a worker (README divergence list;
kvstore.py KVStoreTPUSync). These entry points keep scripts that probe
DMLC_ROLE importable and explain the mapping instead of hanging."""

import os

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer(object):
    """Accepted for API parity; run() documents the divergence."""

    def __init__(self, kvstore):
        self.kvstore = kvstore

    def run(self):
        raise RuntimeError(
            "dist_tpu_sync has no server role: aggregation happens as an "
            "XLA all-reduce across worker processes (launch them with "
            "tools/launch.py; every rank calls kvstore.create("
            "'dist_tpu_sync') and pushes/pulls synchronously)")


def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "worker")
    if role in ("server", "scheduler"):
        raise RuntimeError(
            "DMLC_ROLE=%s requested, but the TPU build runs no ps-lite "
            "roles — relaunch every process as a worker via "
            "tools/launch.py (rendezvous replaces the scheduler)" % role)
