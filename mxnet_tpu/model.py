"""Checkpointing helpers + legacy FeedForward model.

Reference: python/mxnet/model.py:394-472 (save_checkpoint/load_checkpoint
with prefix-NNNN.params + prefix-symbol.json) and the legacy FeedForward
estimator-style API.
"""

import logging
from collections import namedtuple

import numpy as np

from . import ndarray as nd
from . import symbol as sym
from . import io as mx_io
from . import metric as mx_metric
from . import optimizer as opt
from .base import MXNetError

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params", "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """model.py:394 — saves prefix-symbol.json + prefix-NNNN.params."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_params(prefix, epoch):
    """model.py:442 — returns (arg_params, aux_params)."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """model.py:472 — returns (symbol, arg_params, aux_params)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward(object):
    """Legacy estimator API (model.py:544). Thin adapter over Module."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from . import initializer as init_mod
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.kwargs = kwargs.copy()
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self._module = None

    def _get_module(self, data, label_name="softmax_label"):
        from .module import Module
        data_names = [x[0] for x in data.provide_data]
        label_names = [x[0] for x in data.provide_label] or [label_name]
        mod = Module(self.symbol, data_names=data_names,
                     label_names=label_names, context=self.ctx)
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        if not isinstance(X, mx_io.DataIter):
            X = mx_io.NDArrayIter(X, y, batch_size=self.numpy_batch_size,
                                  shuffle=True)
        self._module = self._get_module(X)
        self._module.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                         epoch_end_callback=epoch_end_callback,
                         batch_end_callback=batch_end_callback,
                         optimizer=self.optimizer,
                         optimizer_params=self.kwargs,
                         initializer=self.initializer,
                         arg_params=self.arg_params,
                         aux_params=self.aux_params,
                         begin_epoch=self.begin_epoch,
                         num_epoch=self.num_epoch)
        self.arg_params, self.aux_params = self._module.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        if not isinstance(X, mx_io.DataIter):
            X = mx_io.NDArrayIter(X, None, batch_size=self.numpy_batch_size)
        if self._module is None:
            self._module = self._get_module(X)
            self._module.bind(data_shapes=X.provide_data, for_training=False)
            self._module.set_params(self.arg_params, self.aux_params or {})
        if reset:
            X.reset()
        outputs = []
        for batch in X:
            self._module.forward(batch, is_train=False)
            outputs.append(self._module.get_outputs()[0].asnumpy())
            if num_batch is not None and len(outputs) >= num_batch:
                break
        return np.concatenate(outputs)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params,
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
