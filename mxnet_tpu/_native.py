"""Loader for the native (C++) runtime components under src/.

The reference ships its IO/runtime layer as C++ (dmlc-core recordio,
threaded iter_image_recordio_2.cc); here the native pieces are compiled
on first use with the system toolchain into a cached shared library and
bound through ctypes — no pybind11/pip dependency. Every native entry
point has a pure-Python fallback at its call site, so the package works
(slower) when no compiler is available.
"""

import ctypes
import hashlib
import os
import subprocess
import sys
import threading

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src")

_lock = threading.Lock()
_recordio_lib = None
_recordio_tried = False


def _cache_dir():
    base = os.environ.get("MXNET_NATIVE_CACHE",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "mxnet_tpu"))
    os.makedirs(base, exist_ok=True)
    return base


def _build(source_path, tag, extra_flags=()):
    with open(source_path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_cache_dir(), "lib%s_%s.so" % (tag, digest))
    if os.path.exists(out):
        return out
    # per-process tmp name: concurrent cold-cache builds (data-loader
    # workers) must not interleave into one file; os.replace makes the
    # last finished build win atomically
    tmp = "%s.%d.tmp" % (out, os.getpid())
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           source_path] + list(extra_flags) + ["-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return out


def recordio_lib():
    """The compiled recordio scanner/reader, or None when unavailable."""
    global _recordio_lib, _recordio_tried
    with _lock:
        if _recordio_tried:
            return _recordio_lib
        _recordio_tried = True
        src = os.path.join(_SRC_DIR, "io", "recordio_scan.cc")
        try:
            lib = ctypes.CDLL(_build(src, "recordio_scan"))
        except Exception as exc:
            print("mxnet_tpu: native recordio unavailable (%s); "
                  "using the pure-Python path" % exc, file=sys.stderr)
            return None
        lib.mxtpu_recordio_scan.restype = ctypes.c_int64
        lib.mxtpu_recordio_scan.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
        lib.mxtpu_recordio_free.argtypes = [
            ctypes.POINTER(ctypes.c_int64)]
        lib.mxtpu_recordio_read.restype = ctypes.c_int64
        lib.mxtpu_recordio_read.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int]
        _recordio_lib = lib
        return lib


def recordio_scan(path):
    """(header_offsets, payload_lengths) int64 arrays for the logical
    records of a .rec file, or None when the native path is unavailable
    or the file is malformed (caller falls back to Python)."""
    lib = recordio_lib()
    if lib is None:
        return None
    offs = ctypes.POINTER(ctypes.c_int64)()
    lens = ctypes.POINTER(ctypes.c_int64)()
    n = lib.mxtpu_recordio_scan(path.encode(), ctypes.byref(offs),
                                ctypes.byref(lens))
    if n < 0:
        return None
    try:
        offsets = np.ctypeslib.as_array(offs, shape=(n,)).copy() if n \
            else np.zeros(0, np.int64)
        lengths = np.ctypeslib.as_array(lens, shape=(n,)).copy() if n \
            else np.zeros(0, np.int64)
    finally:
        if n:
            lib.mxtpu_recordio_free(offs)
            lib.mxtpu_recordio_free(lens)
    return offsets, lengths


def recordio_read(path, offsets, lengths, num_threads=4):
    """Payload bytes of the records at `offsets` (list of bytes objects),
    read by the native thread pool; None -> caller falls back."""
    lib = recordio_lib()
    if lib is None:
        return None
    offsets = np.ascontiguousarray(offsets, np.int64)
    lengths = np.ascontiguousarray(lengths, np.int64)
    total = int(lengths.sum())
    buf = ctypes.create_string_buffer(total)
    got = lib.mxtpu_recordio_read(
        path.encode(),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(offsets), buf, int(num_threads))
    if got != total:
        return None
    view = memoryview(buf)
    out = []
    pos = 0
    for n in lengths:
        out.append(bytes(view[pos:pos + int(n)]))
        pos += int(n)
    return out


_libsvm_lib = None
_libsvm_tried = False


def libsvm_lib():
    """The compiled LibSVM parser, or None when unavailable."""
    global _libsvm_lib, _libsvm_tried
    with _lock:
        if _libsvm_tried:
            return _libsvm_lib
        _libsvm_tried = True
        src = os.path.join(_SRC_DIR, "io", "libsvm_scan.cc")
        try:
            lib = ctypes.CDLL(_build(src, "libsvm_scan"))
        except Exception:
            return None
        lib.libsvm_count_rows.restype = ctypes.c_int64
        lib.libsvm_count_rows.argtypes = [ctypes.c_char_p]
        lib.libsvm_parse_dense.restype = ctypes.c_int64
        lib.libsvm_parse_dense.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64]
        lib.libsvm_parse_file.restype = ctypes.c_int64
        lib.libsvm_parse_file.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float))]
        lib.libsvm_free.restype = None
        lib.libsvm_free.argtypes = [ctypes.c_void_p]
        _libsvm_lib = lib
        return lib


def libsvm_parse(path, dim):
    """Parse a LibSVM file into (data[rows, dim] float32, labels[rows])
    with ONE file read (libsvm_parse_file allocates, we copy + free).
    Returns None when the native parser is unavailable or rejects the
    file (caller falls back to the Python parser)."""
    lib = libsvm_lib()
    if lib is None:
        return None
    data_p = ctypes.POINTER(ctypes.c_float)()
    labels_p = ctypes.POINTER(ctypes.c_float)()
    rows = lib.libsvm_parse_file(path.encode(), dim,
                                 ctypes.byref(data_p),
                                 ctypes.byref(labels_p))
    if rows < 0:
        return None
    try:
        data = np.ctypeslib.as_array(data_p, shape=(rows, dim)).copy()             if rows else np.zeros((0, dim), np.float32)
        labels = np.ctypeslib.as_array(labels_p, shape=(rows,)).copy()             if rows else np.zeros((0,), np.float32)
    finally:
        lib.libsvm_free(data_p)
        lib.libsvm_free(labels_p)
    return data, labels


_im2rec_lib = None
_im2rec_tried = False


def im2rec_lib():
    """The compiled im2rec packer (needs OpenCV C++), or None."""
    global _im2rec_lib, _im2rec_tried
    with _lock:
        if _im2rec_tried:
            return _im2rec_lib
        _im2rec_tried = True
        src = os.path.join(_SRC_DIR, "io", "im2rec_pack.cc")
        flags = ["-I/usr/include/opencv4", "-lopencv_imgcodecs",
                 "-lopencv_imgproc", "-lopencv_core"]
        try:
            lib = ctypes.CDLL(_build(src, "im2rec_pack", flags))
        except Exception:
            return None
        lib.mxtpu_im2rec_pack.restype = ctypes.c_int64
        lib.mxtpu_im2rec_pack.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        _im2rec_lib = lib
        return lib


def im2rec_pack(list_path, root, rec_path, idx_path, resize=0,
                quality=95, color=1, num_threads=4, use_png=False,
                quiet=False):
    """Pack the .lst entries into rec/idx natively; returns the packed
    count, or None when the native packer is unavailable (caller falls
    back to the Python loop)."""
    lib = im2rec_lib()
    if lib is None:
        return None
    err = ctypes.create_string_buffer(256)
    n = lib.mxtpu_im2rec_pack(
        list_path.encode(), root.encode(), rec_path.encode(),
        (idx_path or "").encode(), int(resize), int(quality), int(color),
        int(num_threads), int(bool(use_png)), int(bool(quiet)), err, 256)
    if n < 0:
        print("mxnet_tpu: native im2rec failed (%s); using the Python "
              "packer" % err.value.decode(), file=sys.stderr)
        return None
    return int(n)
