"""Sub-microsecond os.environ reads for per-dispatch flag checks.

os._Environ.get costs ~1 us per call (key encode + MutableMapping
plumbing) — too much for code on the eager/CachedOp dispatch path
(~10 us/op budget, benchmark/opperf.py --dispatch). On CPython the
environment is backed by a plain dict (os.environ._data) that putenv/
monkeypatch mutate in place, so reading through it is both fast and
toggle-correct. Non-CPython layouts fall back to os.environ.
"""

import os

_DATA = getattr(os.environ, "_data", None)
if not isinstance(_DATA, dict):          # pragma: no cover - non-CPython
    _DATA = None
_KEYS = {}


def get(name, default=None):
    """os.environ.get at plain-dict speed (~0.1 us)."""
    if _DATA is None:                    # pragma: no cover - non-CPython
        return os.environ.get(name, default)
    key = _KEYS.get(name)
    if key is None:
        key = _KEYS[name] = os.environ.encodekey(name)
    raw = _DATA.get(key)
    if raw is None:
        return default
    return os.environ.decodevalue(raw)
