"""2-bit gradient compression with error-feedback residual.

Reference: src/kvstore/gradient_compression.h:38-132 and the
quantize_2bit/dequantize kernels in gradient_compression-inl.h. Semantics
reproduced exactly:

  residual += grad
  code = 11 (-> +threshold) where residual >=  threshold
  code = 10 (-> -threshold) where residual <= -threshold
  code = 00 (->  0)         otherwise
  residual -= dequantize(code)

16 gradient values pack into one 32-bit word (2 bits each), so the wire
size is 1/16th of fp32 — GetCompressionFactor() == 16 in the reference.

TPU-native: the pack/unpack are pure jnp integer ops compiled by XLA, so
quantization fuses with the surrounding collective instead of running as
a separate engine op; the residual is functional state threaded by the
caller (KVStore keeps one per (key, worker))."""

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["GradientCompression"]

_VALUES_PER_WORD = 16  # 2 bits x 16 = one uint32


@jax.jit
def _quantize_2bit(flat_grad, residual, threshold):
    """Returns (packed uint32 codes, new residual)."""
    acc = residual + flat_grad
    pos = acc >= threshold
    neg = acc <= -threshold
    # 2-bit codes matching the reference bitmasks: 11 = +t, 10 = -t, 00 = 0
    codes = jnp.where(pos, jnp.uint32(3), jnp.where(neg, jnp.uint32(2),
                                                    jnp.uint32(0)))
    emitted = jnp.where(pos, threshold, jnp.where(neg, -threshold, 0.0))
    new_residual = acc - emitted
    n = codes.shape[0]
    pad = (-n) % _VALUES_PER_WORD
    codes = jnp.pad(codes, (0, pad))
    words = codes.reshape(-1, _VALUES_PER_WORD)
    # value i of a word occupies bits [30-2i, 31-2i] (first value in the
    # highest bits, mirroring the reference's byte-then-2-bit layout)
    shifts = jnp.uint32(30 - 2 * np.arange(_VALUES_PER_WORD))
    packed = jnp.bitwise_or.reduce(words << shifts, axis=1)
    return packed, new_residual


@jax.jit
def _dequantize_2bit(packed, threshold):
    """uint32 words -> flat float32 of length 16*len(packed)."""
    shifts = jnp.uint32(30 - 2 * np.arange(_VALUES_PER_WORD))
    codes = (packed[:, None] >> shifts) & jnp.uint32(3)
    vals = jnp.where(codes == 3, threshold,
                     jnp.where(codes == 2, -threshold, 0.0))
    return vals.reshape(-1).astype(jnp.float32)


class GradientCompression(object):
    """Factory + stateless kernels; the caller owns residual arrays."""

    def __init__(self, type="none", threshold=0.5):
        if type not in ("none", "2bit"):
            raise ValueError("Unsupported compression type %s "
                             "(supported: none, 2bit)" % type)
        if type == "2bit" and not threshold > 0:
            raise ValueError("threshold must be positive for 2bit "
                             "compression, got %s" % threshold)
        self.type = type
        self.threshold = float(threshold)

    @property
    def active(self):
        return self.type == "2bit"

    def get_compression_factor(self):
        return _VALUES_PER_WORD if self.active else 1

    def compressed_size(self, original_size):
        """Words needed for `original_size` fp32 values (reference
        GetCompressedSize, in elements not bytes)."""
        if not self.active:
            return original_size
        return -(-original_size // _VALUES_PER_WORD)

    def init_residual(self, shape, dtype=jnp.float32):
        return jnp.zeros((int(np.prod(shape)),), dtype)

    def quantize(self, grad, residual):
        """grad: any-shape array; residual: flat array of grad.size.
        Returns (packed codes, updated residual)."""
        flat = grad.reshape(-1).astype(jnp.float32)
        return _quantize_2bit(flat, residual, self.threshold)

    def dequantize(self, packed, shape):
        n = int(np.prod(shape))
        return _dequantize_2bit(packed, self.threshold)[:n].reshape(shape)

    def compress_decompress(self, grad, residual):
        """One worker step: quantize with error feedback, return the
        reconstructed (dequantized) gradient and new residual — what the
        server would see after the wire round-trip."""
        packed, new_residual = self.quantize(grad, residual)
        return self.dequantize(packed, grad.shape), new_residual
