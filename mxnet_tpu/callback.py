"""Training callbacks.

Reference: python/mxnet/callback.py (do_checkpoint, log_train_metric,
Speedometer, ProgressBar, LogValidationMetricsCallback).
"""

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Checkpoint callback over a module (callback.py:33)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint to prefix-NNNN.params (callback.py:60)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    """Log metric every `period` batches (callback.py:87)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset_local()
    return _callback


class Speedometer(object):
    """Throughput + metric logging every `frequent` batches.

    API contract per reference callback.py:117: logs
    "Epoch[e] Batch [a-b] Speed: s samples/sec metric=value...", resets
    the local metric each report when auto_reset, and restarts its
    window when the batch counter rewinds (new epoch)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._window_start = None     # (batch count, wall time) anchor

    def _report(self, param, speed, lo, hi):
        metric = param.eval_metric
        if metric is None:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, hi, speed)
            return
        pairs = metric.get_name_value()
        if self.auto_reset:
            metric.reset_local()
        fields = "".join("\t%s=%f" % p for p in pairs)
        logging.info("Epoch[%d] Batch [%d-%d]\tSpeed: %.2f samples/sec%s",
                     param.epoch, lo, hi, speed, fields)

    def __call__(self, param):
        count = param.nbatch
        anchor = self._window_start
        if anchor is None or anchor[0] > count:
            # first call, or the counter rewound (epoch rollover):
            # re-anchor without reporting
            self._window_start = (count, time.time())
            return
        if count % self.frequent != 0 or count == anchor[0]:
            return
        elapsed = time.time() - anchor[1]
        samples = (count - anchor[0]) * self.batch_size
        speed = samples / elapsed if elapsed > 0 else float("inf")
        self._report(param, speed, count - self.frequent, count)
        self._window_start = (count, time.time())


class ProgressBar(object):
    """ASCII progress bar (callback.py:188)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


class LogValidationMetricsCallback(object):
    """Log validation metrics at epoch end (callback.py:213)."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name, value)
