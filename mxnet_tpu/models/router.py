"""SLO-aware request router over N ContinuousBatcher replicas.

One batcher is one device loop; scaling the serving story the way the
TensorFlow system paper (PAPERS.md) scales one graph over many workers
means putting a ROUTER in front of N replicas and feeding it live load
signals. This module is that router, built on exactly the signals PR 7
exported for it:

* **Routing** — each admission goes to the healthiest replica by its
  ``/healthz``-shaped snapshot (``ContinuousBatcher.health_snapshot()``
  for in-process replicas; the identical names ride the
  ``MXNET_OBS_HTTP`` ``/healthz`` ``counters`` for a scraped fleet):
  paged KV headroom (``serving.kv_available_blocks``) first, free lanes
  otherwise, lane utilization as the tiebreak.
* **SLO-aware admission** — a replica whose rolling
  ``serving.slo_attainment`` sits below ``slo_floor``
  (``MXNET_ROUTER_SLO_FLOOR``) stops taking NEW admissions until it
  recovers; its live streams keep decoding.
* **Shedding** — when no replica can admit and the backlog exceeds
  ``shed_queue`` (``MXNET_ROUTER_SHED_QUEUE``), the newest queued
  requests are shed: the ``serving.slo_violation.shed`` counter
  increments, the caller sees ``None`` for that rid, and the router
  keeps serving instead of hanging.
* **Failure draining** — a replica whose dispatch dies for good (the
  PR 6 requeue path re-raises after its consecutive-failure cap) is
  marked dead and DRAINED: its live requests go back to the front of
  the router queue as continuations from their synced token prefix, so
  greedy streams resume bit-exactly on a surviving replica (sampled
  streams continue on a deterministically reseeded chain, the PR 6
  recovery contract). Name replicas (``ContinuousBatcher(name="r1")``)
  and a chaos spec like ``serving.dispatch.r1:error:every=1:count=0``
  kills exactly one replica of the pool, replayably.

The replicas are process- or thread-local (the CPU smoke runs them in
one process; telemetry is process-global, so per-replica SLO attainment
degrades to the shared rolling window there — occupancy and block
headroom are per-instance either way).

    srv = ReplicaRouter.build(params, cfg, n_replicas=2, max_batch=4,
                              paged=True)
    results, order = srv.run(jobs)          # {rid: tokens-or-None}
"""

import time
from collections import deque

import numpy as np

from .serving import ContinuousBatcher
from .. import _fastenv
from ..observability import core as _obs

__all__ = ["ReplicaRouter"]


class _Job(object):
    __slots__ = ("rid", "prompt", "n_new", "seed", "stop_token",
                 "enq_ns")

    def __init__(self, rid, prompt, n_new, seed, stop_token, enq_ns):
        self.rid = rid
        self.prompt = list(prompt)
        self.n_new = int(n_new)
        self.seed = int(seed)
        self.stop_token = stop_token
        self.enq_ns = enq_ns


class ReplicaRouter(object):
    """Route a request queue over N ContinuousBatcher replicas (see the
    module docstring for the policy). The API mirrors the batcher's:
    ``submit()`` enqueues and returns a router-level rid, ``step()``
    admits + steps every live replica and returns ``{rid: tokens}``
    for completions (``None`` marks a shed request), ``run(jobs)``
    drives a whole workload. Every completed stream equals its solo
    ``generate()`` output — the per-replica identity the batcher
    already guarantees, preserved across re-routing."""

    def __init__(self, replicas, shed_queue=None, slo_floor=None):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        for i, r in enumerate(self.replicas):
            if r.name is None:
                r.name = "r%d" % i
                r._chaos_site = "serving.dispatch.%s" % r.name
        self._alive = [True] * len(self.replicas)
        if shed_queue is None:
            v = _fastenv.get("MXNET_ROUTER_SHED_QUEUE")
            shed_queue = int(v) if v else None
        self.shed_queue = shed_queue
        if slo_floor is None:
            v = _fastenv.get("MXNET_ROUTER_SLO_FLOOR")
            slo_floor = float(v) if v else None
        self.slo_floor = slo_floor
        self._queue = deque()          # _Job, oldest first
        self._next_rid = 0
        # (replica_idx, replica_rid) -> (router_rid, _Job)
        self._live = {}
        self.shed_rids = []

    @classmethod
    def build(cls, params, cfg, n_replicas=2, shed_queue=None,
              slo_floor=None, **batcher_kw):
        """Construct n named replicas (r0..rN-1) over shared params and
        front them — the one-liner the bench and smoke use."""
        reps = [ContinuousBatcher(params, cfg, name="r%d" % i,
                                  **batcher_kw)
                for i in range(n_replicas)]
        return cls(reps, shed_queue=shed_queue, slo_floor=slo_floor)

    # ---- queueing ----

    @property
    def alive_count(self):
        return sum(self._alive)

    @property
    def active_count(self):
        """Live requests across the fleet (admitted, not finished)."""
        return len(self._live)

    def submit(self, prompt, n_new, seed=0, stop_token=None):
        """Enqueue one request; returns its router-level rid. Admission
        happens at the next step(), on whichever replica the routing
        policy picks."""
        rid = self._next_rid
        self._next_rid += 1
        enq = time.perf_counter_ns() if _obs.enabled() else None
        self._queue.append(_Job(rid, prompt, n_new, seed, stop_token,
                                enq))
        return rid

    # ---- routing policy ----

    def _eligible(self):
        """Replicas that may take NEW admissions this round: alive,
        lane+block capacity, and (when slo_floor is set) rolling SLO
        attainment at or above the floor — best headroom first."""
        scored = []
        for i, r in enumerate(self.replicas):
            if not self._alive[i] or not r.has_capacity:
                continue
            snap = r.health_snapshot()
            att = snap.get("serving.slo_attainment")
            if self.slo_floor is not None and att is not None \
                    and att < self.slo_floor:
                continue
            headroom = snap.get("serving.kv_available_blocks")
            if headroom is None:
                headroom = r.max_batch - snap["serving.lane_occupancy"]
            scored.append((-headroom,
                           snap["serving.lane_utilization"], i))
        return [i for _, _, i in sorted(scored)]

    def _admit_queued(self, finished):
        while self._queue:
            order = self._eligible()
            if not order:
                break
            job = self._queue[0]
            admitted = False
            for i in order:
                rep_rid = self.replicas[i].admit(
                    job.prompt, job.n_new, seed=job.seed,
                    stop_token=job.stop_token, enqueued_ns=job.enq_ns)
                if rep_rid is not None:
                    self._queue.popleft()
                    self._live[(i, rep_rid)] = (job.rid, job)
                    if _obs.enabled():
                        _obs.counter("router.routed").add(1)
                    admitted = True
                    break
            if not admitted:
                break
        # shed the backlog the fleet cannot absorb (newest first —
        # the oldest waiters keep their place)
        if self.shed_queue is not None:
            while len(self._queue) > self.shed_queue:
                job = self._queue.pop()
                self.shed_rids.append(job.rid)
                finished[job.rid] = None
                _obs.counter("serving.slo_violation.shed").add(1)
                if _obs.enabled():
                    _obs.counter("router.shed").add(1)
                    _obs.record_instant(
                        "router.shed", cat="serving",
                        args={"rid": job.rid,
                              "queued": len(self._queue)})

    def _drain_replica(self, i, exc):
        """Replica i's dispatch died for good: mark it dead and put its
        live requests back at the FRONT of the queue as continuations
        from their synced token prefix — the same resume identity as
        the in-replica requeue (cache is a pure function of the
        prefix), so greedy streams stay bit-exact on whichever replica
        picks them up. Sampled continuations are deterministically
        reseeded (seed folded with the emission count)."""
        self._alive[i] = False
        rep = self.replicas[i]
        drained = []
        for (ri, rep_rid), (rid, job) in sorted(self._live.items()):
            if ri != i:
                continue
            req = next((r for r in rep._slots
                        if r is not None and r.rid == rep_rid), None)
            del self._live[(ri, rep_rid)]
            if req is None:
                continue
            cont = _Job(rid, req.tokens,
                        req.n_new - req.emitted,
                        (job.seed * 1000003 + req.emitted) & 0x7fffffff,
                        req.stop_token, job.enq_ns)
            drained.append(cont)
        for cont in reversed(drained):
            self._queue.appendleft(cont)
        if _obs.enabled():
            _obs.counter("router.replica_failures").add(1)
            _obs.counter("router.drained_requests").add(len(drained))
            _obs.record_instant(
                "router.replica_failed", cat="serving",
                args={"replica": rep.name, "drained": len(drained),
                      "error": "%s: %s" % (type(exc).__name__, exc)})

    # ---- scheduling ----

    def step(self):
        """One fleet scheduling round: admit what the policy allows,
        shed what it must, step every live replica (draining any that
        die), and return ``{router_rid: tokens}`` for requests that
        finished — ``None`` for shed ones. Raises the last replica
        failure when NO replica survives (the fleet cannot make
        progress; callers own the restart policy above that)."""
        finished = {}
        self._admit_queued(finished)
        last_exc = None
        for i, rep in enumerate(self.replicas):
            if not self._alive[i]:
                continue
            try:
                done = rep.step()
            except Exception as exc:   # noqa: BLE001 — drain-or-raise
                last_exc = exc
                self._drain_replica(i, exc)
                continue
            for rep_rid, toks in done.items():
                key = (i, rep_rid)
                if key in self._live:
                    rid, _ = self._live.pop(key)
                    finished[rid] = toks
        if not any(self._alive):
            raise last_exc if last_exc is not None else RuntimeError(
                "no live replicas")
        if _obs.enabled():
            _obs.gauge("router.queue_depth").set(len(self._queue))
            _obs.gauge("router.replicas_alive").set(self.alive_count)
            # fleet-wide speculative health: the WORST alive replica's
            # acceptance ratio (the one an operator would retune
            # spec_k for) — absent when no replica speculates
            ratios = [
                r.health_snapshot().get("serving.spec_draft_ratio")
                for i, r in enumerate(self.replicas) if self._alive[i]]
            ratios = [x for x in ratios if x is not None]
            if ratios:
                _obs.gauge("router.spec_accept_ratio").set(min(ratios))
        return finished

    def run(self, requests):
        """Serve ``(prompt, n_new[, seed[, stop_token]])`` jobs through
        the fleet. Returns ({rid: tokens-or-None-if-shed}, submission
        order) — same contract as ContinuousBatcher.run() plus the
        shed marker."""
        order = [self.submit(*job) for job in requests]
        results = {}
        while self._queue or self._live:
            results.update(self.step())
        return results, order
