"""SLO-aware request router over N ContinuousBatcher replicas.

One batcher is one device loop; scaling the serving story the way the
TensorFlow system paper (PAPERS.md) scales one graph over many workers
means putting a ROUTER in front of N replicas and feeding it live load
signals. This module is that router, built on exactly the signals PR 7
exported for it:

* **Routing** — each admission goes to the healthiest replica by its
  ``/healthz``-shaped snapshot (``ContinuousBatcher.health_snapshot()``
  for in-process replicas; the identical names ride the
  ``MXNET_OBS_HTTP`` ``/healthz`` ``counters`` for a scraped fleet):
  paged KV headroom (``serving.kv_available_blocks``) first, free lanes
  otherwise, lane utilization as the tiebreak.
* **Priorities + deadlines** — ``submit(..., priority=, deadline_ms=)``
  orders admission by priority class (larger first), oldest-first
  within a class; a queued request whose deadline has passed — or is
  infeasible given its queue position and the measured
  ``serving.ttft_ms``/``serving.itl_ms`` medians — is EXPIRED up front
  (``serving.slo_violation.expired``) instead of wasting a prefill.
  With uniform priority and no deadlines the queue is plain FIFO,
  bit-identical to the pre-priority router.
* **SLO-aware admission** — a replica whose rolling
  ``serving.slo_attainment`` sits below ``slo_floor``
  (``MXNET_ROUTER_SLO_FLOOR``) stops taking NEW admissions until it
  recovers; its live streams keep decoding.
* **Shedding** — when no replica can admit and the backlog exceeds
  ``shed_queue`` (``MXNET_ROUTER_SHED_QUEUE``), the lowest-priority
  newest queued requests are shed: the ``serving.slo_violation.shed``
  counter increments, the caller sees ``None`` for that rid, and the
  router keeps serving instead of hanging. Shed and expired are
  separate counters — one is a capacity decision, the other a deadline
  fact.
* **Preemption absorption** — a replica that preempted low-priority
  lanes to cover a high-priority admission (``serving.preemptions``)
  hands the victims to the router, which requeues them at the front of
  their priority class as continuations; they resume BIT-exactly vs
  solo ``generate()`` (greedy and sampled — the batcher replays the
  per-step key chain from the original seed).
* **Failure draining + circuit breakers** — a replica whose dispatch
  dies for good (the PR 6 requeue path re-raises after its
  consecutive-failure cap) is DRAINED: its live requests go back to
  the front of the router queue as continuations from their synced
  token prefix, resuming bit-exactly on a surviving replica. Without
  ``MXNET_ROUTER_BREAKER=1`` the drained replica is dead for good (the
  pre-breaker contract); with it the replica enters a breaker loop —
  CLOSED -> OPEN (capped exponential backoff, counted in router
  steps) -> HALF_OPEN (one canary request routed normally and answered
  bit-exactly) -> CLOSED — and the all-dead re-raise only fires once
  every breaker is OPEN with its retries exhausted.
  ``router.replica_state.<name>`` gauges export the machine (0=closed,
  1=half_open, 2=open). Name replicas (``ContinuousBatcher(name="r1")``)
  and a chaos spec like ``serving.dispatch.r1:error:at=6;...:at=7;
  ...:at=8;...:at=9`` kills exactly one replica for exactly one drain
  (four consecutive failures trip the batcher's re-raise), replayably.

The replicas are process- or thread-local (the CPU smoke runs them in
one process; telemetry is process-global, so per-replica SLO attainment
degrades to the shared rolling window there — occupancy and block
headroom are per-instance either way).

    srv = ReplicaRouter.build(params, cfg, n_replicas=2, max_batch=4,
                              paged=True)
    results, order = srv.run(jobs)          # {rid: tokens-or-None}
"""

import os
import time
import warnings
from collections import deque

from . import journal as _journal
from .serving import ContinuousBatcher
from .. import _fastenv
from ..observability import chaos as _chaos
from ..observability import core as _obs
from ..observability import events as _events
from ..observability import flight as _flight
from ..observability import membudget as _membudget
from ..observability import timeseries as _ts

__all__ = ["ReplicaRouter"]

_STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}
# rolling-rollout phases, gauge-coded for /healthz scrapers
_ROLLOUT_CODE = {"idle": 0, "draining": 1, "canary": 2, "done": 3,
                 "rolled_back": 4}


class _Job(object):
    __slots__ = ("rid", "prompt", "n_new", "seed", "stop_token",
                 "enq_ns", "priority", "deadline_ns", "emitted",
                 "preempt_ns", "key", "fp", "prompt0", "n0")

    def __init__(self, rid, prompt, n_new, seed, stop_token, enq_ns,
                 priority=0, deadline_ns=None, emitted=0,
                 preempt_ns=None, key=None, fp=None, prompt0=None,
                 n0=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.n_new = int(n_new)
        self.seed = int(seed)
        self.stop_token = stop_token
        self.enq_ns = enq_ns
        self.priority = int(priority)
        self.deadline_ns = deadline_ns
        # emitted > 0 marks a CONTINUATION: `prompt` is the full synced
        # token prefix (original prompt + emitted tokens), `n_new` the
        # remaining budget, `seed` the ORIGINAL submit seed (the
        # batcher replays the key chain `emitted` steps from it)
        self.emitted = int(emitted)
        self.preempt_ns = preempt_ns
        self.key = key                 # idempotency key (dedup window)
        # weight-version affinity: a continuation resumes only on a
        # replica serving the fingerprint its prefix was computed under
        self.fp = fp
        # the ORIGINAL submission (restart-from-origin fallback when
        # affinity cannot be satisfied mid-rollout)
        self.prompt0 = list(prompt0) if prompt0 is not None \
            else list(prompt)
        self.n0 = int(n0) if n0 is not None else int(n_new)


class ReplicaRouter(object):
    """Route a request queue over N ContinuousBatcher replicas (see the
    module docstring for the policy). The API mirrors the batcher's:
    ``submit()`` enqueues and returns a router-level rid, ``step()``
    admits + steps every live replica and returns ``{rid: tokens}``
    for completions (``None`` marks a shed or expired request —
    ``shed_rids``/``expired_rids`` tell them apart), ``run(jobs)``
    drives a whole workload. Every completed stream equals its solo
    ``generate()`` output — the per-replica identity the batcher
    already guarantees, preserved across re-routing, preemption and
    breaker revival."""

    def __init__(self, replicas, shed_queue=None, slo_floor=None,
                 breaker=None, breaker_backoff=None,
                 breaker_backoff_max=None, breaker_retries=None,
                 journal=None, rollout_attain=None,
                 rollout_window=None):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        for i, r in enumerate(self.replicas):
            if r.name is None:
                r.name = "r%d" % i
                r._chaos_site = "serving.dispatch.%s" % r.name
        self._alive = [True] * len(self.replicas)
        if shed_queue is None:
            v = _fastenv.get("MXNET_ROUTER_SHED_QUEUE")
            shed_queue = int(v) if v else None
        self.shed_queue = shed_queue
        if slo_floor is None:
            v = _fastenv.get("MXNET_ROUTER_SLO_FLOOR")
            slo_floor = float(v) if v else None
        self.slo_floor = slo_floor
        if breaker is None:
            breaker = (_fastenv.get("MXNET_ROUTER_BREAKER") or "") \
                not in ("", "0", "false", "False")
        self.breaker = bool(breaker)
        if breaker_backoff is None:
            v = _fastenv.get("MXNET_ROUTER_BREAKER_BACKOFF")
            breaker_backoff = int(v) if v else 2
        self._breaker_backoff = max(1, int(breaker_backoff))
        if breaker_backoff_max is None:
            v = _fastenv.get("MXNET_ROUTER_BREAKER_BACKOFF_MAX")
            breaker_backoff_max = int(v) if v else 32
        self._breaker_backoff_max = max(self._breaker_backoff,
                                        int(breaker_backoff_max))
        if breaker_retries is None:
            v = _fastenv.get("MXNET_ROUTER_BREAKER_RETRIES")
            breaker_retries = int(v) if v else 5
        self._breaker_retries = max(0, int(breaker_retries))
        n = len(self.replicas)
        self._brk_state = ["closed"] * n
        self._brk_backoff = [self._breaker_backoff] * n
        self._brk_open_left = [0] * n     # step countdown while OPEN
        self._brk_trips = [0] * n         # consecutive drains
        self._brk_canary = [None] * n     # router rid probing HALF_OPEN
        self.breaker_events = []          # (name, from_state, to_state)
        self._queue = deque()          # _Job, oldest first
        self._next_rid = 0
        # (replica_idx, replica_rid) -> (router_rid, _Job)
        self._live = {}
        self.shed_rids = []
        self.expired_rids = []
        self._last_exc = None
        self._fp_warned = None   # last mixed weight-version set warned
        # the router's own write-ahead journal covers its QUEUE (the
        # replicas journal their admitted streams into per-name
        # subdirectories of the same MXNET_SERVING_JOURNAL_DIR):
        # submit() appends an emitted=0 record, admission tombstones it
        # (reason "admit" — the replica's record takes over), a
        # drain/preemption requeue re-journals the continuation here
        # and tombstones the replica's record (reason "resume")
        if journal is None:
            jd = _fastenv.get("MXNET_SERVING_JOURNAL_DIR")
            journal = _journal.RequestJournal(
                os.path.join(jd, "router")) if jd else False
        elif isinstance(journal, str):
            journal = _journal.RequestJournal(journal)
        self._journal = journal or None
        # idempotency dedup window (keys also pass through to the
        # replica, so its journal-backed window survives a crash)
        self._idem = {}
        self._idem_done = {}
        self._redeliver = {}     # rid -> tokens, served at next step()
        # rolling weight rollout (start_rollout / _rollout_tick)
        self._rollout = None
        self.rollout_events = []     # (event, detail) audit trail
        if rollout_attain is None:
            v = _fastenv.get("MXNET_ROUTER_ROLLOUT_ATTAIN")
            rollout_attain = float(v) if v else None
        self.rollout_attain = rollout_attain
        if rollout_window is None:
            v = _fastenv.get("MXNET_ROUTER_ROLLOUT_WINDOW")
            rollout_window = int(v) if v else 8
        self.rollout_window = max(1, int(rollout_window))
        # fleet trend aggregation (PR 17): each replica's health
        # snapshot retained per step as a bounded fleet time-series,
        # with the timeseries.py detectors run over it — anomalies
        # count into obs.anomaly.* and warn once per (detector,
        # replica) until the condition clears
        self._fleet_hist = {}        # replica name -> deque of dicts
        self._anomaly_warned = set()
        # flight-recorder context: incident bundles carry the fleet
        # view (weakly held — registration never pins the router)
        _flight.register_context("router", self.health_snapshot)

    @classmethod
    def build(cls, params, cfg, n_replicas=2, shed_queue=None,
              slo_floor=None, breaker=None, **batcher_kw):
        """Construct n named replicas (r0..rN-1) over shared params and
        front them — the one-liner the bench and smoke use."""
        reps = [ContinuousBatcher(params, cfg, name="r%d" % i,
                                  **batcher_kw)
                for i in range(n_replicas)]
        return cls(reps, shed_queue=shed_queue, slo_floor=slo_floor,
                   breaker=breaker)

    # ---- queueing ----

    @property
    def alive_count(self):
        return sum(self._alive)

    @property
    def active_count(self):
        """Live requests across the fleet (admitted, not finished)."""
        return len(self._live)

    def submit(self, prompt, n_new, seed=0, stop_token=None,
               priority=0, deadline_ms=None, key=None):
        """Enqueue one request; returns its router-level rid. Admission
        happens at the next step(), on whichever replica the routing
        policy picks — higher `priority` admits first (FIFO within a
        class), and a `deadline_ms` budget (from now) lets the router
        expire the request up front instead of serving it late.
        `key` is an idempotency key: a duplicate submission returns
        the ORIGINAL rid (still live: keep waiting on it; finished:
        the recorded result re-delivers at the next step()) instead of
        double-serving — ``serving.dedup_hits`` counts the hits, and
        with a journal attached the window survives restarts."""
        if key is not None:
            hit = self._idem.get(key)
            if hit is None and key in self._idem_done:
                rid0, toks0 = self._idem_done[key]
                self._redeliver[rid0] = list(toks0)
                hit = rid0
            if hit is not None:
                _obs.counter("serving.dedup_hits").add(1)
                if _obs.enabled():
                    _obs.record_instant(
                        "router.dedup", cat="serving",
                        args={"rid": hit, "key": str(key)})
                return hit
        rid = self._next_rid
        self._next_rid += 1
        now = (time.perf_counter_ns()
               if (deadline_ms is not None or _obs.enabled()) else None)
        enq = now if _obs.enabled() else None
        ddl = (None if deadline_ms is None
               else now + int(deadline_ms * 1e6))
        job = _Job(rid, prompt, n_new, seed, stop_token, enq,
                   priority=priority, deadline_ns=ddl, key=key)
        self._queue.append(job)
        if key is not None:
            self._idem[key] = rid
        if self._journal is not None:
            # emitted=0: a pure queue entry — recovery re-enqueues it
            # whole (deadlines are wall-clock local and do not survive)
            self._journal.append_submit(
                rid, job.prompt, n_new, seed=seed,
                stop_token=stop_token, priority=priority, key=key,
                emitted=0)
        return rid

    # ---- routing policy ----

    def _eligible(self, job=None, ignore_affinity=False):
        """Replicas that may take NEW admissions this round: alive,
        lane+block capacity, and (when slo_floor is set) rolling SLO
        attainment at or above the floor — best headroom first. A
        HALF_OPEN replica is eligible only while its canary slot is
        unclaimed, and bypasses the SLO floor (the probe must be able
        to run while the very attainment it is meant to restore is
        depressed). With a `job` in hand, a replica with a free lane
        but NO block headroom still qualifies — ranked last — when it
        runs strictly-lower-priority work, because preempting that
        work can fund the admission (the batcher's own admit() makes
        the final call). During a rollout the current target takes
        nothing, and a CONTINUATION routes version-affinely: only to a
        replica serving the fingerprint its prefix was computed under
        (``router.weight_version_mismatch`` counts the skips —
        _admit_queued owns the restart-from-origin fallback when no
        affine replica remains)."""
        scored = []
        ro = self._rollout
        for i, r in enumerate(self.replicas):
            if not self._alive[i]:
                continue
            if ro is not None and ro["phase"] in ("draining", "canary") \
                    and i == ro["idx"]:
                continue           # rollout target: drains, takes none
            if not ignore_affinity and job is not None \
                    and job.emitted > 0 and job.fp is not None \
                    and r.weight_fingerprint != job.fp:
                # resuming under different weights would splice two
                # models into one stream — the mismatch counter is the
                # GATE here, not just an alarm
                _obs.counter("router.weight_version_mismatch").add(1)
                continue
            preempt_only = False
            if not r.has_capacity:
                if job is None or not getattr(r, "paged", False) \
                        or r.active_count >= r.max_batch \
                        or not any(q is not None
                                   and q.priority < job.priority
                                   for q in r._slots):
                    continue
                preempt_only = True
            half_open = self._brk_state[i] == "half_open"
            if half_open and self._brk_canary[i] is not None:
                continue
            snap = r.health_snapshot()
            att = snap.get("serving.slo_attainment")
            if not half_open and self.slo_floor is not None \
                    and att is not None and att < self.slo_floor:
                continue
            mem_hb = snap.get("mem.headroom_bytes")
            if mem_hb is not None \
                    and mem_hb < _membudget.reserve_bytes():
                # device memory starved below the configured reserve:
                # routing new work there would only trip its OOM
                # recovery — steer admissions elsewhere until the
                # snapshot shows headroom again
                continue
            headroom = snap.get("serving.kv_available_blocks")
            if headroom is None:
                headroom = r.max_batch - snap["serving.lane_occupancy"]
            scored.append((preempt_only, -headroom,
                           snap["serving.lane_utilization"], i))
        return [i for _, _, _, i in sorted(scored)]

    def _fleet_lanes(self):
        return sum(r.max_batch for i, r in enumerate(self.replicas)
                   if self._alive[i])

    def _eta_ms(self, job, ahead):
        """Optimistic completion estimate for a queued job with `ahead`
        jobs of its priority class (or higher) in front of it, from the
        measured latency medians: each wave of `fleet_lanes` admissions
        costs one median stream (TTFT + n_new ITLs). Returns None when
        the histograms are empty — never expire on no data."""
        ttft = _obs.histogram("serving.ttft_ms", "ms")
        itl = _obs.histogram("serving.itl_ms", "ms")
        if not ttft.count or not itl.count:
            return None
        lanes = self._fleet_lanes()
        if lanes <= 0:
            return None
        per = ttft.percentile(0.5) + job.n_new * itl.percentile(0.5)
        return (ahead // lanes + 1) * per

    def _expire_queued(self, finished):
        """Walk the queue and expire every job whose deadline already
        passed, or whose optimistic ETA (queue position x measured
        medians) overruns the time it has left. An expiry costs the
        caller nothing but the wait so far; serving it would cost a
        prefill and block a lane for a stream nobody can use."""
        if not any(j.deadline_ns is not None for j in self._queue):
            return
        now = time.perf_counter_ns()
        keep = deque()
        for job in self._queue:
            expired = False
            if job.deadline_ns is not None:
                left_ms = (job.deadline_ns - now) / 1e6
                if left_ms <= 0:
                    expired = True
                else:
                    ahead = sum(1 for j in keep
                                if j.priority >= job.priority)
                    eta = self._eta_ms(job, ahead)
                    expired = eta is not None and eta > left_ms
            if not expired:
                keep.append(job)
                continue
            self.expired_rids.append(job.rid)
            finished[job.rid] = None
            self._retire_job(job, "expired")
            _obs.counter("serving.slo_violation.expired").add(1)
            if _obs.enabled():
                _obs.counter("router.expired").add(1)
                _events.event("expire", rid=job.rid,
                              priority=job.priority)
        self._queue = keep

    def _admit_queued(self, finished):
        self._expire_queued(finished)
        while self._queue:
            # strict priority, FIFO within a class (max() returns the
            # FIRST maximal element, so uniform priorities reduce
            # exactly to the old head-of-line scan)
            job = max(self._queue, key=lambda j: j.priority)
            order = self._eligible(job)
            if not order:
                if job.emitted > 0 and job.fp is not None \
                        and self._eligible(job, ignore_affinity=True):
                    # every replica serving this stream's weight
                    # version is gone (mid-rollout): restart from the
                    # ORIGINAL prompt so the whole stream comes from
                    # ONE version instead of splicing two
                    job.emitted = 0
                    job.prompt = list(job.prompt0)
                    job.n_new = job.n0
                    job.fp = None
                    _obs.counter("router.rollout_restarts").add(1)
                    continue
                break
            admitted = False
            for i in order:
                rep = self.replicas[i]
                if job.emitted > 0:
                    rep_rid = rep.admit_continuation(
                        job.prompt, job.n_new, seed=job.seed,
                        emitted=job.emitted,
                        stop_token=job.stop_token,
                        priority=job.priority,
                        preempted_ns=job.preempt_ns, key=job.key)
                else:
                    rep_rid = rep.admit(
                        job.prompt, job.n_new, seed=job.seed,
                        stop_token=job.stop_token,
                        enqueued_ns=job.enq_ns,
                        priority=job.priority, key=job.key)
                if rep_rid is not None:
                    self._queue.remove(job)
                    self._live[(i, rep_rid)] = (job.rid, job)
                    if self._journal is not None:
                        # the replica's record owns the stream now
                        self._journal.append_finish(job.rid, "admit")
                    if self.breaker \
                            and self._brk_state[i] == "half_open" \
                            and self._brk_canary[i] is None:
                        self._brk_canary[i] = job.rid
                    if rep.preempted:
                        self._absorb_preempted(i, rep)
                    if _obs.enabled():
                        _obs.counter("router.routed").add(1)
                        _events.event(
                            "admit", rid=job.rid, replica=rep.name,
                            priority=job.priority,
                            continuation=job.emitted > 0)
                    admitted = True
                    break
            if not admitted:
                break
        # shed the backlog the fleet cannot absorb: lowest priority
        # first, newest within a class — the oldest high-priority
        # waiters keep their place
        if self.shed_queue is not None:
            while len(self._queue) > self.shed_queue:
                ix = min(range(len(self._queue)),
                         key=lambda k: (self._queue[k].priority, -k))
                job = self._queue[ix]
                del self._queue[ix]
                self.shed_rids.append(job.rid)
                finished[job.rid] = None
                self._retire_job(job, "shed")
                _obs.counter("serving.slo_violation.shed").add(1)
                if _obs.enabled():
                    _obs.counter("router.shed").add(1)
                    _events.event("shed", rid=job.rid,
                                  priority=job.priority,
                                  queued=len(self._queue))

    def _retire_job(self, job, reason):
        """A queued job left the router for good (shed / expired):
        release its idempotency claim and tombstone its journal
        record so GC can truncate the segment."""
        if job.key is not None and self._idem.get(job.key) == job.rid:
            self._idem.pop(job.key, None)
        if self._journal is not None:
            self._journal.append_finish(job.rid, reason)

    def _requeue_cont(self, rep, rep_rid, cont):
        """A stream moved OFF a replica back into the router queue:
        the router's journal record takes ownership (fresh submit with
        the synced prefix) and the replica's record is tombstoned —
        a crash at any point replays exactly one of the two."""
        if self._journal is not None:
            self._journal.append_submit(
                cont.rid, cont.prompt, cont.n_new, seed=cont.seed,
                stop_token=cont.stop_token, priority=cont.priority,
                key=cont.key, emitted=cont.emitted)
        if rep._journal is not None:
            rep._journal.append_finish(rep_rid, "resume")

    def _absorb_preempted(self, i, rep):
        """Replica i preempted low-priority lanes to cover an
        admission — move the victims into the router queue as
        continuations at the front of the line (priority selection
        still ranks them below the high-priority work that displaced
        them). Their resume is bit-exact: full synced prefix, original
        seed, cumulative emission count for the key-chain replay."""
        conts = []
        for req, t_ns in rep.preempted:
            entry = self._live.pop((i, req.rid), None)
            if entry is None:
                continue               # not routed by us — drop
            rid, job = entry
            cont = _Job(rid, req.tokens,
                        req.n_new - req.emitted, job.seed,
                        req.stop_token, job.enq_ns,
                        priority=job.priority,
                        deadline_ns=job.deadline_ns,
                        emitted=req.emitted, preempt_ns=t_ns,
                        key=job.key, fp=rep.weight_fingerprint,
                        prompt0=job.prompt0, n0=job.n0)
            self._requeue_cont(rep, req.rid, cont)
            conts.append(cont)
        rep.preempted = []
        for cont in reversed(conts):
            self._queue.appendleft(cont)

    def _drain_replica(self, i, exc, finished):
        """Replica i's dispatch died for good: take it out of rotation
        and put its live requests back at the FRONT of the queue as
        continuations from their synced token prefix — the same resume
        identity as the in-replica requeue (cache is a pure function
        of the prefix, the sampling key chain is replayed from the
        original seed), so completed streams stay bit-exact on
        whichever replica picks them up. Without the breaker the
        replica is dead permanently; with it the breaker opens with
        capped exponential backoff and the replica's state is rebuilt
        (``reset_lanes``) ahead of its HALF_OPEN canary."""
        self._alive[i] = False
        rep = self.replicas[i]
        drained = []
        for (ri, rep_rid), (rid, job) in sorted(self._live.items()):
            if ri != i:
                continue
            req = next((r for r in rep._slots
                        if r is not None and r.rid == rep_rid), None)
            del self._live[(ri, rep_rid)]
            if req is None:
                continue
            if req.n_new - req.emitted <= 0:
                # complete at the instant of death — nothing to resume
                finished[rid] = list(req.tokens)
                if rep._journal is not None:
                    rep._journal.append_finish(rep_rid, "finish",
                                               tokens=req.tokens)
                if job.key is not None:
                    if self._idem.get(job.key) == rid:
                        self._idem.pop(job.key, None)
                    self._idem_done[job.key] = (rid, list(req.tokens))
                continue
            cont = _Job(rid, req.tokens,
                        req.n_new - req.emitted, job.seed,
                        req.stop_token, job.enq_ns,
                        priority=job.priority,
                        deadline_ns=job.deadline_ns,
                        emitted=req.emitted, key=job.key,
                        fp=rep.weight_fingerprint,
                        prompt0=job.prompt0, n0=job.n0)
            self._requeue_cont(rep, rep_rid, cont)
            drained.append(cont)
        for cont in reversed(drained):
            self._queue.appendleft(cont)
        if self.breaker:
            self._brk_trips[i] += 1
            self._brk_canary[i] = None
            if self._brk_trips[i] <= self._breaker_retries:
                try:
                    rep.reset_lanes()
                except Exception:      # noqa: BLE001 — stay broken
                    self._brk_trips[i] = self._breaker_retries + 1
            if self._brk_trips[i] <= self._breaker_retries:
                self._brk_open_left[i] = self._brk_backoff[i]
                self._brk_backoff[i] = min(
                    self._brk_backoff[i] * 2, self._breaker_backoff_max)
            self._transition(i, "open")
        if _obs.enabled():
            _obs.counter("router.replica_failures").add(1)
            _obs.counter("router.drained_requests").add(len(drained))
            _obs.record_instant(
                "router.replica_failed", cat="serving",
                args={"replica": rep.name, "drained": len(drained),
                      "error": "%s: %s" % (type(exc).__name__, exc)})

    # ---- circuit breaker ----

    def _transition(self, i, state):
        """Move replica i's breaker to `state`, record the transition
        (``breaker_events``, instant, gauge)."""
        old = self._brk_state[i]
        if old == state:
            return
        self._brk_state[i] = state
        self.breaker_events.append((self.replicas[i].name, old, state))
        if _obs.enabled():
            _obs.gauge("router.replica_state.%s"
                       % self.replicas[i].name).set(_STATE_CODE[state])
            _events.event("breaker", replica=self.replicas[i].name,
                          frm=old, to=state,
                          trips=self._brk_trips[i])
            if state == "open":
                _flight.record_incident(
                    "breaker.open", replica=self.replicas[i].name,
                    trips=self._brk_trips[i],
                    backoff=self._brk_open_left[i])

    def _breaker_tick(self, i):
        """One router step elapsed for an OPEN replica: count the
        backoff down; at zero enter HALF_OPEN — back in rotation for
        exactly one canary admission."""
        if self._brk_state[i] != "open" \
                or self._brk_trips[i] > self._breaker_retries:
            return
        self._brk_open_left[i] -= 1
        if self._brk_open_left[i] <= 0:
            self._brk_canary[i] = None
            self._alive[i] = True
            self._transition(i, "half_open")

    def _breaker_close(self, i):
        """The canary finished bit-exactly: the replica is healthy —
        close the breaker and forget the failure history."""
        self._brk_trips[i] = 0
        self._brk_backoff[i] = self._breaker_backoff
        self._brk_canary[i] = None
        self._transition(i, "closed")

    # ---- scheduling ----

    def step(self):
        """One fleet scheduling round: expire what cannot make its
        deadline, admit what the policy allows, shed what it must,
        step every live replica (draining any that die, ticking open
        breakers), and return ``{router_rid: tokens}`` for requests
        that finished — ``None`` for shed/expired ones. Raises the
        last replica failure only when NO replica can ever make
        progress again: every one dead (breaker off) or every breaker
        OPEN with its retries exhausted (breaker on); callers own the
        restart policy above that."""
        finished = {}
        if self._redeliver:
            # deduped already-finished streams (idempotency hits and
            # journal recovery) re-deliver here, no dispatch spent
            finished.update(self._redeliver)
            self._redeliver.clear()
        if self._rollout is not None \
                and self._rollout["phase"] == "draining":
            self._rollout_tick(finished)
        self._admit_queued(finished)
        last_exc = None
        for i, rep in enumerate(self.replicas):
            if not self._alive[i]:
                if self.breaker:
                    self._breaker_tick(i)
                continue
            try:
                done = rep.step()
            except Exception as exc:   # noqa: BLE001 — drain-or-raise
                last_exc = exc
                self._last_exc = exc
                self._drain_replica(i, exc, finished)
                continue
            if rep.preempted:
                self._absorb_preempted(i, rep)
            for rep_rid, toks in done.items():
                if self._rollout is not None \
                        and self._rollout.get("canary") == (i, rep_rid):
                    # the rollout's synthetic probe, not client work
                    self._rollout_canary_done(i, toks, finished)
                    continue
                key = (i, rep_rid)
                if key in self._live:
                    rid, job = self._live.pop(key)
                    finished[rid] = toks
                    if job.key is not None:
                        if self._idem.get(job.key) == rid:
                            self._idem.pop(job.key, None)
                        self._idem_done[job.key] = (rid, list(toks))
                    if self.breaker and self._brk_canary[i] == rid:
                        self._breaker_close(i)
        if not any(self._alive):
            exhausted = (not self.breaker) or all(
                t > self._breaker_retries for t in self._brk_trips)
            if exhausted:
                exc = last_exc if last_exc is not None \
                    else self._last_exc
                raise exc if exc is not None else RuntimeError(
                    "no live replicas")
        if _obs.enabled():
            _obs.gauge("router.queue_depth").set(len(self._queue))
            _obs.gauge("router.replicas_alive").set(self.alive_count)
            for i, r in enumerate(self.replicas):
                _obs.gauge("router.replica_state.%s" % r.name).set(
                    _STATE_CODE[self._brk_state[i]])
            # one health snapshot per alive replica feeds BOTH the
            # fleet gauges and the trend history below
            snaps = {i: r.health_snapshot()
                     for i, r in enumerate(self.replicas)
                     if self._alive[i]}
            # fleet-wide speculative health: the WORST alive replica's
            # acceptance ratio (the one an operator would retune
            # spec_k for) — absent when no replica speculates
            ratios = [
                s.get("serving.spec_draft_ratio")
                for s in snaps.values()]
            ratios = [x for x in ratios if x is not None]
            if ratios:
                _obs.gauge("router.spec_accept_ratio").set(min(ratios))
            self._record_fleet_history(snaps)
            _obs.gauge("router.rollout_phase").set(
                _ROLLOUT_CODE[self._rollout["phase"]]
                if self._rollout else 0)
            self._check_weight_versions()
        if self._rollout is not None \
                and self._rollout["phase"] == "done" \
                and self._rollout["watch_left"] > 0:
            # post-swap SLO watch: a fleet whose attainment collapses
            # right after an upgrade rolls back even though every
            # canary matched (the canary proves numerics, not load)
            ro = self._rollout
            ro["watch_left"] -= 1
            if self.rollout_attain is not None:
                bad = [r.name for i, r in enumerate(self.replicas)
                       if self._alive[i]
                       and (r.health_snapshot()
                            .get("serving.slo_attainment") or 1.0)
                       < self.rollout_attain]
                if bad:
                    self._rollback_fleet(
                        finished, "post-swap SLO collapse on %s"
                        % ",".join(bad))
        return finished

    # ---- rolling weight rollout ----

    def start_rollout(self, params, manifest=None, canary_tokens=None):
        """Begin a zero-downtime rolling upgrade of the fleet to
        `params`, verified against PR 13's checkpoint lineage BEFORE
        any replica is touched: `manifest` is a checkpoint directory
        (``verify_lineage`` must pass and its ``param_fingerprint``
        must match the incoming tree) or a manifest dict; bad lineage
        raises ``CheckpointCorrupt`` with the fleet untouched.

        The upgrade then proceeds one replica at a time, driven by
        step(): the target stops taking admissions, its live streams
        requeue onto still-affine replicas (version-affine routing),
        the drained replica hot-swaps (``swap_weights`` — membudget
        preflight, drain-then-swap degradation), and a BIT-EXACT
        canary (a synthetic probe checked against solo ``generate()``
        under the new weights, `canary_tokens` long —
        ``MXNET_ROUTER_ROLLOUT_CANARY_TOKENS``, default 4) gates the
        next replica. A diverged canary, a failed swap, or a post-swap
        SLO collapse (``MXNET_ROUTER_ROLLOUT_ATTAIN`` over
        ``MXNET_ROUTER_ROLLOUT_WINDOW`` steps) AUTO-ROLLS-BACK every
        already-upgraded replica to the prior verified fingerprint —
        live streams survive the rollback (the swap preserves them).
        Returns the target fingerprint."""
        from . import checkpoint as _ckpt
        from ..observability import integrity as _integrity
        if self._rollout is not None \
                and self._rollout["phase"] in ("draining", "canary"):
            raise RuntimeError("a rollout is already in progress")
        want = None
        if isinstance(manifest, str):
            chain = _ckpt.verify_lineage(manifest)
            if not chain or chain[0]["status"] != "verified":
                raise _ckpt.CheckpointCorrupt(
                    "start_rollout: lineage of %s does not verify (%s)"
                    % (manifest, chain[0]["status"] if chain
                       else "no manifests"))
            import json as _json
            with open(os.path.join(manifest, chain[0]["name"])) as f:
                want = _json.load(f).get("param_fingerprint")
        elif isinstance(manifest, dict):
            want = manifest.get("param_fingerprint")
        new_fp = _integrity.params_fingerprint(params)
        if want is not None and new_fp != want:
            raise _ckpt.CheckpointCorrupt(
                "start_rollout: incoming parameter fingerprint %s "
                "does not match manifest %s — refusing unverified "
                "weights" % (new_fp, want))
        if canary_tokens is None:
            v = _fastenv.get("MXNET_ROUTER_ROLLOUT_CANARY_TOKENS")
            canary_tokens = int(v) if v else 4
        self._rollout = {
            "params": params, "manifest": manifest, "fp": new_fp,
            "prior": [r.params for r in self.replicas],
            "prior_fp": [r.weight_fingerprint for r in self.replicas],
            "phase": "draining", "idx": 0, "canary": None,
            "expected": None, "canary_tokens": max(1, canary_tokens),
            "watch_left": self.rollout_window,
        }
        self.rollout_events.append(("start", new_fp))
        if _obs.enabled():
            _events.event("swap", phase="start", fingerprint=new_fp,
                          replicas=len(self.replicas))
        return new_fp

    @property
    def rollout_phase(self):
        return self._rollout["phase"] if self._rollout else "idle"

    def _rollout_tick(self, finished):
        """One draining-phase round for the current target: requeue
        its live streams (they resume version-affinely elsewhere),
        and once it is empty, swap + launch the canary."""
        ro = self._rollout
        i = ro["idx"]
        rep = self.replicas[i]
        if not self._alive[i]:
            # a dead replica has nothing to drain or swap — its
            # breaker canary re-verifies whatever weights it holds
            # if it ever recovers
            self.rollout_events.append(("skipped_dead", rep.name))
            self._rollout_advance()
            return
        self._rollout_drain(i, finished)
        if rep.preempted:
            self._absorb_preempted(i, rep)
        if rep.active_count == 0 and not rep.preempted:
            self._rollout_swap(i, finished)

    def _rollout_drain(self, i, finished):
        """Move the target's live streams back into the router queue
        as continuations from their synced prefixes — the same resume
        identity as a replica drain, but the replica stays healthy
        (cancel() frees each lane; nothing in flight is lost)."""
        rep = self.replicas[i]
        conts = []
        for (ri, rep_rid), (rid, job) in sorted(self._live.items()):
            if ri != i:
                continue
            req = next((r for r in rep._slots
                        if r is not None and r.rid == rep_rid), None)
            del self._live[(ri, rep_rid)]
            if req is None:
                continue
            if req.n_new - req.emitted <= 0:
                finished[rid] = list(req.tokens)
                if job.key is not None:
                    if self._idem.get(job.key) == rid:
                        self._idem.pop(job.key, None)
                    self._idem_done[job.key] = (rid, list(req.tokens))
                if rep._journal is not None:
                    # a crash replays this as finished, not canceled
                    rep._journal.append_finish(
                        rep_rid, "finish", tokens=req.tokens)
                rep.cancel(rep_rid)
                continue
            cont = _Job(rid, req.tokens, req.n_new - req.emitted,
                        job.seed, req.stop_token, job.enq_ns,
                        priority=job.priority,
                        deadline_ns=job.deadline_ns,
                        emitted=req.emitted, key=job.key,
                        fp=rep.weight_fingerprint,
                        prompt0=job.prompt0, n0=job.n0)
            rep.cancel(rep_rid)    # journal-tombstoned (reason cancel)
            if self._journal is not None:
                self._journal.append_submit(
                    cont.rid, cont.prompt, cont.n_new, seed=cont.seed,
                    stop_token=cont.stop_token,
                    priority=cont.priority, key=cont.key,
                    emitted=cont.emitted)
            conts.append(cont)
        for cont in reversed(conts):
            self._queue.appendleft(cont)
        if conts and _obs.enabled():
            _obs.counter("router.rollout_drained").add(len(conts))

    def _rollout_swap(self, i, finished):
        """The drained target hot-swaps and admits its bit-exact
        canary probe. Any swap failure rolls the fleet back."""
        ro = self._rollout
        rep = self.replicas[i]
        try:
            if _chaos.enabled():
                _chaos.fire("router.rollout", replica=rep.name,
                            phase="swap")
            rep.swap_weights(ro["params"], manifest=ro["manifest"])
        except Exception as exc:       # noqa: BLE001 — rollback
            self._rollback_fleet(
                finished, "swap failed on %s: %s: %s"
                % (rep.name, type(exc).__name__, exc))
            return
        import numpy as np
        from . import transformer as tf
        n_tok = ro["canary_tokens"]
        prompt = [1 % rep.cfg.vocab_size, 2 % rep.cfg.vocab_size,
                  3 % rep.cfg.vocab_size]
        expected = [int(t) for t in np.asarray(tf.generate(
            rep.params, np.asarray([prompt]), n_tok, rep.cfg,
            greedy=rep.greedy, seed=0))[0]]
        rep_rid = rep.admit(prompt, n_tok, seed=0)
        if rep_rid is None:
            self._rollback_fleet(
                finished, "canary admission refused on %s" % rep.name)
            return
        ro["canary"] = (i, rep_rid)
        ro["expected"] = expected
        ro["phase"] = "canary"
        self.rollout_events.append(("canary", rep.name))

    def _rollout_canary_done(self, i, toks, finished):
        """The canary probe finished: bit-exact against solo
        generate() under the new weights closes this replica's
        upgrade; ANY divergence (or an injected ``router.rollout``
        fault — the chaos site for a canary that lies) rolls the
        fleet back."""
        ro = self._rollout
        rep = self.replicas[i]
        try:
            if _chaos.enabled():
                _chaos.fire("router.rollout", replica=rep.name,
                            phase="canary")
            ok = list(toks) == ro["expected"]
        except Exception:              # noqa: BLE001 — divergence
            ok = False
        if not ok:
            self._rollback_fleet(
                finished, "canary diverged on %s" % rep.name)
            return
        ro["canary"] = None
        self.rollout_events.append(("upgraded", rep.name))
        if _obs.enabled():
            _events.event("swap", phase="upgraded", replica=rep.name,
                          fingerprint=ro["fp"])
        self._rollout_advance()

    def _rollout_advance(self):
        ro = self._rollout
        ro["idx"] += 1
        if ro["idx"] >= len(self.replicas):
            ro["phase"] = "done"
            ro["watch_left"] = self.rollout_window
            self.rollout_events.append(("done", ro["fp"]))
        else:
            ro["phase"] = "draining"

    def _rollback_fleet(self, finished, reason):
        """Roll every already-upgraded replica back to the PRIOR
        verified fingerprint (captured at start_rollout — rollback
        needs no manifest re-verification, those exact params were
        serving before). Live streams survive: swap_weights preserves
        them, and the canary probe is canceled, not a client
        stream."""
        ro = self._rollout
        if ro.get("canary") is not None:
            ci, crid = ro["canary"]
            self.replicas[ci].cancel(crid)
            ro["canary"] = None
        for i, rep in enumerate(self.replicas):
            if rep.weight_fingerprint == ro["prior_fp"][i]:
                continue
            try:
                rep.swap_weights(ro["prior"][i])
            except Exception as exc:   # noqa: BLE001 — drain it
                self._drain_replica(i, exc, finished)
        ro["phase"] = "rolled_back"
        ro["reason"] = reason
        _obs.counter("router.rollbacks").add(1)
        self.rollout_events.append(("rolled_back", reason))
        if _obs.enabled():
            _events.event("rollback", reason=reason,
                          restored=[fp for fp in ro["prior_fp"]])
            _flight.record_incident("rollout.rollback", reason=reason,
                                    target_fp=ro["fp"],
                                    restored=[fp for fp in
                                              ro["prior_fp"]])
        warnings.warn(
            "router: rollout of %s rolled back — %s"
            % (ro["fp"], reason), RuntimeWarning, stacklevel=2)

    # ---- crash recovery ----

    def recover(self):
        """Replay the router's queue journal AND every replica's own
        journal after a whole-process crash. Queue records (emitted=0
        submits that never reached a replica, and requeued
        continuations) re-enter the router queue; each replica's
        recovered streams are adopted under fresh router rids (their
        completions return from step() like any other), its recorded
        finished streams re-deliver at the next step(), and parked
        overflow moves into the router queue. Returns
        ``(requeued_rids, finished, skipped)``."""
        if self._journal is None:
            raise RuntimeError(
                "recover() needs a journal attached "
                "(MXNET_SERVING_JOURNAL_DIR or journal=)")
        live, fin, skipped = self._journal.replay()
        self._next_rid = max(self._next_rid, self._journal.max_rid + 1)
        done = {}
        for rid, rec in fin.items():
            done[rid] = list(rec["tokens"])
            if rec.get("key") is not None:
                self._idem_done[rec["key"]] = (rid, list(rec["tokens"]))
        requeued = []
        for rid in sorted(live):
            rec = live[rid]
            job = _Job(rid, rec["tokens"], rec["n_new"], rec["seed"],
                       rec["stop"], None, priority=rec["prio"],
                       emitted=rec["emitted"], key=rec.get("key"))
            self._queue.append(job)
            if job.key is not None:
                self._idem[job.key] = rid
            requeued.append(rid)
        for i, rep in enumerate(self.replicas):
            if rep._journal is None:
                continue
            # the pre-recovery view maps old rids to their submit
            # records — recover() itself rewrites the journal
            pre, _pf, _ps = rep._journal.replay()
            resumed, rdone, rskip = rep.recover()
            skipped = skipped + rskip
            for rep_rid, toks in rdone.items():
                rid = self._next_rid
                self._next_rid += 1
                done[rid] = list(toks)
            for old_rid, new_rid in resumed.items():
                if new_rid is None:
                    continue           # parked; absorbed below
                rec = pre.get(old_rid, {})
                rid = self._next_rid
                self._next_rid += 1
                job = _Job(rid, rec.get("tokens", []),
                           max(int(rec.get("n_new", 0))
                               - int(rec.get("emitted", 1)), 1),
                           rec.get("seed", 0), rec.get("stop"), None,
                           priority=rec.get("prio", 0),
                           emitted=rec.get("emitted", 1),
                           key=rec.get("key"))
                self._live[(i, new_rid)] = (rid, job)
                if job.key is not None:
                    self._idem[job.key] = rid
                requeued.append(rid)
            for req, t_ns in rep.preempted:
                # capacity overflow at replica recovery: the router
                # queue owns it now (journal ownership moves too)
                rid = self._next_rid
                self._next_rid += 1
                cont = _Job(rid, req.tokens, req.n_new - req.emitted,
                            req.seed, req.stop_token, None,
                            priority=req.priority, emitted=req.emitted,
                            key=req.key, preempt_ns=t_ns)
                self._requeue_cont(rep, req.rid, cont)
                self._queue.append(cont)
                if cont.key is not None:
                    self._idem[cont.key] = rid
                requeued.append(rid)
            rep.preempted = []
        self._redeliver.update(done)
        if _obs.enabled():
            _obs.counter("router.journal_recoveries").add(1)
            _obs.record_instant(
                "router.recover", cat="serving",
                args={"requeued": len(requeued),
                      "finished": len(done), "skipped": len(skipped)})
        return requeued, done, skipped

    def _check_weight_versions(self):
        """A fleet must serve ONE weight version: after a partial
        weight rollout (or a silently corrupted replica reload) some
        replicas answer from different parameters — per-request
        results then depend on routing luck. Compare the alive
        replicas' cached fingerprints; a mixed fleet bumps
        ``router.weight_version_mismatch`` every scheduling round it
        persists and warns once per distinct mix."""
        fps = {r.name: r.weight_fingerprint
               for i, r in enumerate(self.replicas) if self._alive[i]}
        if len(set(fps.values())) <= 1:
            return
        _obs.counter("router.weight_version_mismatch").add(1)
        mix = frozenset(fps.items())
        if mix != self._fp_warned:
            self._fp_warned = mix
            warnings.warn(
                "router: replicas serve MIXED weight versions: %s — "
                "responses now depend on routing"
                % ", ".join("%s=%s" % kv for kv in sorted(fps.items())),
                RuntimeWarning, stacklevel=2)

    # ---- fleet trend aggregation (PR 17) ----

    def _anomaly_cfg(self):
        def _num(key, default, cast=float):
            v = _fastenv.get(key)
            return cast(v) if v else default
        return {
            "window": max(_num("MXNET_OBS_ANOMALY_WINDOW", 32, int), 4),
            "min_points": max(
                _num("MXNET_OBS_ANOMALY_MIN_POINTS", 8, int), 4),
            "leak_blocks": _num("MXNET_OBS_ANOMALY_LEAK_BLOCKS", 1.0),
            "slide_drop": _num("MXNET_OBS_ANOMALY_SLIDE_DROP", 0.2),
            "collapse_drop": _num("MXNET_OBS_ANOMALY_COLLAPSE_DROP",
                                  0.5),
            "storm": _num("MXNET_OBS_ANOMALY_STORM", 3, int),
        }

    def _record_fleet_history(self, snaps):
        """Retain this step's per-replica health snapshots as a
        bounded fleet time-series and run the trend detectors
        (timeseries.py) over the rings: KV-block leak at idle and SLO
        attainment slide per replica; throughput collapse and retrace
        storm fleet-wide. Only called under ``_obs.enabled()``."""
        cfg = self._anomaly_cfg()
        win = cfg["window"]
        counters = _obs.counters()
        rc_total = sum(c.value for name, c in counters.items()
                       if name.startswith("recompile."))
        gp = counters.get("serving.goodput_tok_s")
        fleet = self._fleet_hist.setdefault(
            "__fleet__", deque(maxlen=win))
        fleet.append({"goodput": gp.value if gp is not None else None,
                      "recompiles": rc_total})
        for i, snap in snaps.items():
            name = self.replicas[i].name
            hist = self._fleet_hist.setdefault(
                name, deque(maxlen=win))
            hist.append({
                "free": snap.get("serving.kv_free_blocks"),
                "occ": snap.get("serving.lane_occupancy", 0),
                "att": snap.get("serving.slo_attainment"),
            })
            self._detect_trends(name, list(hist), cfg)
        self._detect_fleet_trends(list(fleet), cfg)

    def _detect_trends(self, name, hist, cfg):
        free = [(h["free"], h["occ"]) for h in hist
                if h["free"] is not None]
        if free and _ts.detect_leak(
                [f for f, _o in free], [o for _f, o in free],
                min_points=cfg["min_points"],
                min_drop=cfg["leak_blocks"]):
            self._note_anomaly(
                "kv_leak", name,
                "%g free blocks lost while idle"
                % (free[0][0] - free[-1][0]))
        att = [h["att"] for h in hist if h["att"] is not None]
        if _ts.detect_slide(att, drop=cfg["slide_drop"],
                            min_points=cfg["min_points"]):
            self._note_anomaly(
                "slo_slide", name,
                "attainment slid %.2f -> %.2f" % (att[0], att[-1]))

    def _detect_fleet_trends(self, fleet, cfg):
        gp = [h["goodput"] for h in fleet if h["goodput"] is not None]
        if _ts.detect_collapse(gp, drop=cfg["collapse_drop"],
                               min_points=cfg["min_points"]):
            self._note_anomaly(
                "throughput_collapse", "fleet",
                "goodput %.1f -> %.1f tok/s" % (gp[0], gp[-1]))
        rc = [h["recompiles"] for h in fleet]
        deltas = [b - a for a, b in zip(rc, rc[1:])]
        if len(deltas) >= cfg["min_points"] and _ts.detect_storm(
                deltas[-cfg["window"]:], threshold=cfg["storm"]):
            self._note_anomaly(
                "retrace_storm", "fleet",
                "%d recompiles inside the window" % int(sum(deltas)))

    def _note_anomaly(self, detector, where, detail):
        """One detector firing: count ``obs.anomaly.<detector>``, log
        a decision event, and warn ONCE per (detector, where) — the
        counters keep climbing while the condition persists, the
        warning doesn't repeat."""
        _obs.counter("obs.anomaly." + detector).add(1)
        _events.event("anomaly", detector=detector, where=where,
                      detail=detail)
        key = (detector, where)
        if key not in self._anomaly_warned:
            self._anomaly_warned.add(key)
            warnings.warn(
                "router: anomaly %s on %s — %s"
                % (detector, where, detail),
                _ts.AnomalyWarning, stacklevel=3)

    def fleet_history(self, name=None):
        """The retained trend rings (tests + tools): per-replica lists
        of snapshot dicts, plus the ``__fleet__`` ring."""
        if name is not None:
            return list(self._fleet_hist.get(name, ()))
        return {k: list(v) for k, v in self._fleet_hist.items()}

    def health_snapshot(self):
        """Router-level ``/healthz`` mirror: queue + fleet gauges, the
        shed/expired accounting (separate counters — satellite of the
        overload PR), and every replica's breaker state, one dict of
        scrape-shaped names."""
        snap = {
            "router.queue_depth": len(self._queue),
            "router.replicas_alive": self.alive_count,
            "router.active_requests": len(self._live),
            "serving.slo_violation.shed": len(self.shed_rids),
            "serving.slo_violation.expired": len(self.expired_rids),
        }
        for i, r in enumerate(self.replicas):
            snap["router.replica_state.%s" % r.name] = \
                _STATE_CODE[self._brk_state[i]]
        snap["router.weight_versions"] = len(
            {r.weight_fingerprint
             for i, r in enumerate(self.replicas) if self._alive[i]})
        snap["router.rollout_phase"] = _ROLLOUT_CODE[self.rollout_phase]
        if self._rollout is not None:
            snap["router.rollout_target_fp"] = int(
                self._rollout["fp"], 16)
        if self._journal is not None:
            snap["router.journal_depth_bytes"] = \
                self._journal.depth_bytes
            snap["router.journal_lag_records"] = \
                self._journal.lag_records
        for name, c in _obs.counters().items():
            if name.startswith("obs.anomaly."):
                snap[name] = c.value
        return snap

    def run(self, requests):
        """Serve ``(prompt, n_new[, seed[, stop_token[, priority
        [, deadline_ms]]]])`` jobs through the fleet. Returns
        ({rid: tokens-or-None-if-shed-or-expired}, submission order) —
        same contract as ContinuousBatcher.run() plus the shed/expired
        marker (``shed_rids``/``expired_rids`` tell them apart)."""
        order = [self.submit(*job) for job in requests]
        results = {}
        while self._queue or self._live or (
                self._rollout is not None
                and self._rollout["phase"] in ("draining", "canary")):
            results.update(self.step())
        return results, order
