"""Request write-ahead journal — durable serving across process death.

Every serving-visible state change of a request appends ONE CRC-guarded
record to a segment file under ``MXNET_SERVING_JOURNAL_DIR``:

* ``submit`` — admission (or router enqueue): rid, the full token
  prefix (prompt, plus the first generated token for batcher-level
  records — admit() produces it from the prefill logits), remaining
  budget, sampling seed, stop token, priority, deadline, idempotency
  key, and the cumulative ``emitted`` count (>= 1 marks a
  continuation; the sampling key-chain state is exactly
  ``PRNGKey(seed)`` split ``emitted`` times, so recording the count
  records the chain).
* ``emit`` — a chunk-sync checkpoint: the tokens that just became
  host-visible plus the new cumulative count. These ride the existing
  per-chunk host sync (the batcher already pulled the tokens); the
  journal adds no device round trip.
* ``park`` — a preemption: the victim's synced prefix and count, so a
  crash before its resume replays it as a live continuation.
* ``fin`` — a tombstone: finish / cancel / shed / expire / resume,
  with the final token stream for ``finish`` (the idempotent-dedup
  serving copy).

A record is one line, ``"%08x %s\\n" % (crc32(json), json)`` — the
checkpoint manifest's CRC idiom — written with one ``os.write`` on an
``O_APPEND`` descriptor (atomic for line-sized writes on a local
filesystem). A torn tail (no trailing newline, a short line) or a
CRC-mismatched record is SKIPPED at replay with named evidence
(segment, record index, reason): one bad record never poisons the
stream behind it.

Segments rotate at ``segment_bytes`` and a prefix-truncating GC removes
the longest head run of segments whose every request is tombstoned AND
touches no surviving segment — so a live request's records (including
its submit in an old segment) are never truncated. ``replay()``
reconstructs ``(live, finished, skipped)`` for
``ContinuousBatcher.recover()`` / ``ReplicaRouter.recover()``.

Durability knobs: ``MXNET_SERVING_JOURNAL_SEGMENT_BYTES`` (rotation
threshold, default 1 MiB) and ``MXNET_SERVING_JOURNAL_FSYNC=1``
(fsync every append; default off — the journal then survives process
death, which is the serving failure mode, but not host power loss).

Chaos sites: ``journal.append`` fires before every record write (so
``journal.append:crash:at=K:code=9`` kills the process with record K
torn away — the kill -9 replay test) and supports ``bitflip`` at-rest
corruption via ``chaos.corrupt_file``; ``journal.replay`` fires once
per replayed segment.
"""

import json
import os
import zlib

from .. import _fastenv
from ..observability import chaos as _chaos

__all__ = ["RequestJournal"]

DEFAULT_SEGMENT_BYTES = 1 << 20
_SEG_FMT = "seg-%06d.wal"


def _crc_line(payload):
    """``payload`` (bytes) -> the full journal line (bytes)."""
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return b"%08x " % crc + payload + b"\n"


class RequestJournal(object):
    """Segmented request write-ahead log (see the module docstring).

    >>> j = RequestJournal(dirpath)
    >>> j.append_submit(rid, tokens, n_new, seed, stop, priority)
    >>> j.append_emit(rid, new_tokens, emitted)
    >>> j.append_finish(rid, "finish", tokens=stream)
    >>> live, finished, skipped = RequestJournal(dirpath).replay()

    Construction scans every existing segment once (the replay pass),
    then opens a FRESH segment for appends — a recovering process never
    writes into its predecessor's tail.
    """

    def __init__(self, dirpath=None, segment_bytes=None, fsync=None):
        if dirpath is None:
            dirpath = _fastenv.get("MXNET_SERVING_JOURNAL_DIR")
        if not dirpath:
            raise ValueError(
                "RequestJournal needs a directory (argument or "
                "MXNET_SERVING_JOURNAL_DIR)")
        self.dir = dirpath
        os.makedirs(self.dir, exist_ok=True)
        if segment_bytes is None:
            v = _fastenv.get("MXNET_SERVING_JOURNAL_SEGMENT_BYTES")
            segment_bytes = int(v) if v else DEFAULT_SEGMENT_BYTES
        self.segment_bytes = max(1, int(segment_bytes))
        if fsync is None:
            fsync = (_fastenv.get("MXNET_SERVING_JOURNAL_FSYNC") or "") \
                not in ("", "0", "false", "False")
        self.fsync = bool(fsync)
        # per-segment bookkeeping (insertion order == name order):
        # which rids each segment touches, how many valid records and
        # bytes it holds — what GC and the depth/lag gauges read
        self._seg_rids = {}         # seg name -> set(rid)
        self._seg_records = {}      # seg name -> valid record count
        self._seg_bytes = {}        # seg name -> file size
        self._done = set()          # tombstoned rids
        self._max_rid = -1
        self._live, self._finished, self._skipped = {}, {}, {}
        self._scan()
        nxt = 0
        for name in self._seg_rids:
            nxt = max(nxt, int(name[4:-4]) + 1)
        self._next_seg = nxt
        self._fd = None
        self._active = None
        self._active_bytes = 0
        self._rotated = False       # a rotation since the last gc()

    # ---- append path ----

    def _open_segment(self):
        if self._fd is not None:
            os.close(self._fd)
        name = _SEG_FMT % self._next_seg
        self._next_seg += 1
        self._fd = os.open(os.path.join(self.dir, name),
                           os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                           0o644)
        self._active = name
        self._active_bytes = 0
        self._seg_rids[name] = set()
        self._seg_records[name] = 0
        self._seg_bytes[name] = 0

    def _append(self, obj):
        rid = obj["rid"]
        if _chaos.enabled():
            # fires BEFORE the write: a crash rule tears this record
            # away, a delay rule stalls the append, an error rule
            # surfaces as an OSError to the caller's one guarded site
            _chaos.fire("journal.append", type=obj["t"], rid=rid)
        line = _crc_line(json.dumps(obj, separators=(",", ":"),
                                    sort_keys=True).encode())
        if self._fd is None \
                or self._active_bytes >= self.segment_bytes:
            if self._fd is not None:
                self._rotated = True
            self._open_segment()
        os.write(self._fd, line)
        if self.fsync:
            os.fsync(self._fd)
        if _chaos.enabled():
            # at-rest corruption: a journal.append bitflip rule flips
            # one bit of the segment file, replayably
            _chaos.corrupt_file("journal.append",
                                os.path.join(self.dir, self._active))
        self._active_bytes += len(line)
        self._seg_bytes[self._active] += len(line)
        self._seg_records[self._active] += 1
        self._seg_rids[self._active].add(rid)
        self._max_rid = max(self._max_rid, rid)
        self._apply(obj)

    def append_submit(self, rid, tokens, n_new, seed=0, stop_token=None,
                      priority=0, key=None, emitted=0,
                      deadline_ms=None):
        rec = {"t": "submit", "rid": int(rid),
               "tokens": [int(t) for t in tokens], "n_new": int(n_new),
               "seed": int(seed), "stop": stop_token,
               "prio": int(priority), "emitted": int(emitted)}
        if key is not None:
            rec["key"] = key
        if deadline_ms is not None:
            rec["deadline_ms"] = float(deadline_ms)
        self._append(rec)

    def append_emit(self, rid, tokens, emitted):
        self._append({"t": "emit", "rid": int(rid),
                      "tokens": [int(t) for t in tokens],
                      "emitted": int(emitted)})

    def append_park(self, rid, tokens, emitted):
        self._append({"t": "park", "rid": int(rid),
                      "tokens": [int(t) for t in tokens],
                      "emitted": int(emitted)})

    def append_finish(self, rid, reason, tokens=None):
        rec = {"t": "fin", "rid": int(rid), "reason": reason}
        if tokens is not None and reason == "finish":
            rec["tokens"] = [int(t) for t in tokens]
        self._append(rec)

    # ---- replay ----

    def _apply(self, obj):
        """Fold one record into the (live, finished) reconstruction."""
        rid = obj["rid"]
        t = obj["t"]
        if t == "submit":
            self._live[rid] = {
                "tokens": list(obj["tokens"]), "n_new": obj["n_new"],
                "seed": obj.get("seed", 0), "stop": obj.get("stop"),
                "prio": obj.get("prio", 0), "key": obj.get("key"),
                "emitted": obj.get("emitted", 0),
                "deadline_ms": obj.get("deadline_ms")}
            return True
        if rid not in self._live:
            return False               # emit/park/fin for unknown rid
        if t == "emit":
            rec = self._live[rid]
            rec["tokens"].extend(obj["tokens"])
            rec["emitted"] = obj["emitted"]
        elif t == "park":
            rec = self._live[rid]
            rec["tokens"] = list(obj["tokens"])
            rec["emitted"] = obj["emitted"]
        elif t == "fin":
            rec = self._live.pop(rid)
            self._done.add(rid)
            if obj.get("reason") == "finish":
                self._finished[rid] = {
                    "tokens": obj.get("tokens", rec["tokens"]),
                    "reason": "finish", "key": rec.get("key")}
        return True

    def _scan(self):
        """One pass over the existing segments: rebuild the per-segment
        rid/record maps AND the (live, finished, skipped) replay state.
        Torn or CRC-corrupt records are skipped with named evidence."""
        names = sorted(n for n in os.listdir(self.dir)
                       if n.startswith("seg-") and n.endswith(".wal"))
        for name in names:
            path = os.path.join(self.dir, name)
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError as e:
                self._skip(name, -1, "unreadable segment: %s" % e)
                continue
            if _chaos.enabled():
                _chaos.fire("journal.replay", segment=name)
            self._seg_rids[name] = set()
            self._seg_records[name] = 0
            self._seg_bytes[name] = len(data)
            tail_torn = not data.endswith(b"\n")
            lines = data.split(b"\n")
            if lines and lines[-1] == b"":
                lines.pop()
            for idx, line in enumerate(lines):
                if tail_torn and idx == len(lines) - 1:
                    self._skip(name, idx,
                               "torn tail (no record terminator)")
                    continue
                obj = self._parse(name, idx, line)
                if obj is None:
                    continue
                self._seg_records[name] += 1
                self._seg_rids[name].add(obj["rid"])
                self._max_rid = max(self._max_rid, obj["rid"])
                if not self._apply(obj):
                    self._skip(name, idx,
                               "%s record for unknown rid %d"
                               % (obj["t"], obj["rid"]))

    def _parse(self, name, idx, line):
        if len(line) < 10 or line[8:9] != b" ":
            self._skip(name, idx, "malformed record framing")
            return None
        want, payload = line[:8], line[9:]
        got = b"%08x" % (zlib.crc32(payload) & 0xFFFFFFFF)
        if got != want:
            self._skip(name, idx, "crc mismatch (%s != %s)"
                       % (got.decode(), want.decode()))
            return None
        try:
            obj = json.loads(payload.decode())
            if not isinstance(obj, dict) or "t" not in obj \
                    or "rid" not in obj:
                raise ValueError("not a journal record")
            obj["rid"] = int(obj["rid"])
        except (ValueError, TypeError, UnicodeDecodeError) as e:
            self._skip(name, idx, "undecodable payload: %s" % e)
            return None
        return obj

    def _skip(self, name, idx, reason):
        self._skipped.setdefault("evidence", []).append(
            {"segment": name, "record": idx, "reason": reason})

    def replay(self):
        """The reconstructed state: ``(live, finished, skipped)``.
        ``live`` maps rid -> {tokens, n_new, seed, stop, prio, key,
        emitted, deadline_ms} (everything ``admit_continuation`` /
        re-enqueue needs), ``finished`` maps rid -> {tokens, reason,
        key} (the idempotent-dedup serving copies), ``skipped`` is the
        named evidence list for records the scan refused."""
        live = {rid: dict(rec, tokens=list(rec["tokens"]))
                for rid, rec in self._live.items()}
        fin = {rid: dict(rec, tokens=list(rec["tokens"]))
               for rid, rec in self._finished.items()}
        return live, fin, list(self._skipped.get("evidence", []))

    @property
    def max_rid(self):
        """Largest rid any record names (-1 when empty) — a recovering
        batcher bumps its rid counter past it so resumed and fresh
        requests never collide in the same journal."""
        return self._max_rid

    # ---- size / GC ----

    @property
    def depth_bytes(self):
        """Bytes across all surviving segments (the
        ``serving.journal_depth_bytes`` gauge)."""
        return sum(self._seg_bytes.values())

    @property
    def lag_records(self):
        """Valid records a replay would have to read (the
        ``serving.journal_lag_records`` gauge) — GC is what keeps this
        bounded."""
        return sum(self._seg_records.values())

    def gc(self):
        """Prefix-truncating segment GC: remove the longest HEAD run of
        segments in which every request is tombstoned and none touches
        a surviving segment (so no surviving record ever references a
        truncated rid — a live request's segment is never collected,
        and neither is a finished one whose tombstone lives further
        down the log). Returns the removed segment names."""
        names = sorted(self._seg_rids)
        cut, seen = 0, set()
        for k, name in enumerate(names):
            if name == self._active:
                break
            seen |= self._seg_rids[name]
            if not seen <= self._done:
                break
            rest = set()
            for later in names[k + 1:]:
                rest |= self._seg_rids[later]
            if seen & rest:
                continue            # a rid here survives further down
            cut = k + 1
        removed = names[:cut]
        for name in removed:
            try:
                os.remove(os.path.join(self.dir, name))
            except OSError:
                pass                # a lost unlink only delays the GC
            for rid in self._seg_rids[name]:
                self._finished.pop(rid, None)
            self._done -= self._seg_rids[name]
            del self._seg_rids[name]
            del self._seg_records[name]
            del self._seg_bytes[name]
        return removed

    def maybe_gc(self):
        """GC iff a segment rotated since the last collection — the
        cheap per-round tick the batcher calls from ``_end_round``."""
        if not self._rotated:
            return []
        self._rotated = False
        return self.gc()

    def close(self):
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
