"""SPMD transformer language model — the multi-chip flagship.

Built TPU-first rather than ported: a pure-functional decoder LM whose
parameters and activations carry jax.sharding PartitionSpecs over the
framework mesh axes (parallel/__init__.py):

  dp — batch;  tp — heads / FFN hidden (Megatron-style);  sp — sequence
  (ring attention, parallel/ring.py);  ep — MoE experts;  pp — pipeline
  stages (stage-major layer stacking + collective-permute microbatch
  schedule in parallel/pipeline.py).

The reference framework has no transformer model family beyond attention
helper ops (src/operator/contrib/transformer.cc interleaved matmul) —
this module is the capability extension SURVEY §2.3/§5 calls for, and is
what `__graft_entry__.dryrun_multichip` compiles over an N-device mesh.

Everything here is plain JAX (jit-traceable, static shapes); bf16
matmuls with fp32 accumulation target the MXU.
"""

from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.ring import ring_attention, ring_attention_sharded
from ..parallel.pipeline import stack_stage_params, spmd_pipeline

__all__ = ["TransformerConfig", "init_params", "forward", "loss_fn",
           "make_train_step", "param_specs", "init_cache", "decode_step",
           "make_decode_step", "generate", "shard_cache", "prefill",
           "quantize_weights_int8", "beam_search", "prefill_chunk",
           "speculative_generate", "save_checkpoint", "load_checkpoint",
           "restore_train_state", "init_paged_cache", "decode_step_paged",
           "verify_chunk", "verify_chunk_paged"]


@dataclass
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    # grouped-query attention: KV heads (None = n_heads, i.e. MHA).
    # Shrinks the KV cache by n_heads/n_kv_heads — the decode-bandwidth
    # lever; the flash-decode kernel reads each cache block once per
    # GROUP of query heads
    n_kv_heads: int = None
    n_layers: int = 2
    d_ff: int = 128
    n_experts: int = 0          # 0 = dense FFN; >0 = MoE every layer
    max_len: int = 128
    dtype: object = jnp.float32
    # mesh axis names (set to None to disable an axis)
    dp_axis: str = "dp"
    tp_axis: str = "tp"
    sp_axis: str = "sp"
    ep_axis: str = "ep"
    pp_axis: str = None         # set to 'pp' to pipeline the layer stack
    num_microbatches: int = 0   # 0 = one per pipeline stage
    # positional encoding: learned absolute embeddings (the default) or
    # rotary (RoPE) applied to q/k — position-extrapolating and the
    # standard for long-context models; the learned `pos` table is
    # simply unused when rope=True
    rope: bool = False
    rope_base: float = 10000.0
    use_ring_attention: bool = True
    # attention through the Pallas flash kernel (kernels/
    # flash_attention.py): single-device dense path AND the per-shard
    # block compute inside ring attention; sequences (or ring shards)
    # must divide the kernel's blocks
    use_flash_kernel: bool = False
    # activation recompute: checkpoint each transformer layer so backward
    # rematerializes its activations instead of storing them (the
    # reference's MXNET_BACKWARD_DO_MIRROR, src/nnvm/gradient.cc:285,
    # applied at the idiomatic per-layer granularity)
    remat_layers: bool = False
    # serving: int8 KV cache with per-(batch, position, head) scales —
    # halves cache HBM, doubling the slot count or context a chip can
    # hold, and the decode attention stays int8 end to end on the MXU
    # (scales applied outside the contractions; v-scales fold into the
    # softmax probabilities). Decode takes the dense grouped path —
    # the flash kernel reads full-precision caches. ~0.5-1% relative
    # error on attention outputs (tested); weight-only int8
    # (quantize_weights_int8) composes independently.
    kv_cache_int8: bool = False


def _norm_shape(cfg):
    return (cfg.d_model,)


def _kvh(cfg):
    kvh = cfg.n_kv_heads or cfg.n_heads
    if cfg.n_heads % kvh:
        raise ValueError(
            "n_heads=%d must be a multiple of n_kv_heads=%d"
            % (cfg.n_heads, kvh))
    return kvh


def _rope(x, positions, base):
    """Rotary position encoding on [..., T, H, Dh] (or [..., H, Dh]
    with scalar/[B] positions at decode): rotate feature pairs
    (half-split convention) by position-dependent angles."""
    dh = x.shape[-1]
    if dh % 2:
        raise ValueError(
            "rope needs an even head dim, got d_model/n_heads = %d" % dh)
    half = dh // 2
    freqs = (1.0 / base) ** (jnp.arange(half, dtype=jnp.float32) / half)
    ang = jnp.asarray(positions, jnp.float32)[..., None] * freqs
    if jnp.ndim(positions) >= 1:
        # positions carry a T (or batch) axis that aligns with x's -3
        # axis; insert the broadcast head axis
        ang = ang[..., None, :]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([x1 * cos - x2 * sin,
                           x2 * cos + x1 * sin], axis=-1)
    return rot.astype(x.dtype)


def _repeat_kv(x, g):
    """[.., T, KVH, D] -> [.., T, H, D] by repeating each KV head over
    its query group (training/dense paths; the decode kernel maps
    groups natively instead of materializing the repeat)."""
    return x if g == 1 else jnp.repeat(x, g, axis=2)


def param_specs(cfg):
    """PartitionSpec per parameter — Megatron-style TP, experts on ep."""
    tp, ep = cfg.tp_axis, cfg.ep_axis
    layer = {
        "ln1": P(None), "ln2": P(None),
        "wq": P(None, tp, None), "wk": P(None, tp, None),
        "wv": P(None, tp, None), "wo": P(tp, None, None),
    }
    if cfg.n_experts:
        layer.update({
            "gate": P(None, None),
            "w1": P(ep, None, tp), "w2": P(ep, tp, None),
        })
    else:
        layer.update({"w1": P(None, tp), "w2": P(tp, None)})
    out = {
        "embed": P(None, None),
        "ln_f": P(None),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }
    if not cfg.rope:
        out["pos"] = P(None, None)
    return out


def init_params(cfg, seed=0):
    rng = np.random.RandomState(seed)
    dt = cfg.dtype
    hd = cfg.d_model // cfg.n_heads

    def dense(*shape):
        scale = 1.0 / np.sqrt(shape[0] if len(shape) == 2 else cfg.d_model)
        return jnp.asarray(rng.randn(*shape) * scale, dt)

    def layer():
        p = {
            "ln1": jnp.ones(_norm_shape(cfg), dt),
            "ln2": jnp.ones(_norm_shape(cfg), dt),
            "wq": dense(cfg.d_model, cfg.n_heads, hd),
            "wk": dense(cfg.d_model, _kvh(cfg), hd),
            "wv": dense(cfg.d_model, _kvh(cfg), hd),
            "wo": dense(cfg.n_heads, hd, cfg.d_model),
        }
        if cfg.n_experts:
            p["gate"] = dense(cfg.d_model, cfg.n_experts)
            p["w1"] = jnp.asarray(
                rng.randn(cfg.n_experts, cfg.d_model, cfg.d_ff) /
                np.sqrt(cfg.d_model), dt)
            p["w2"] = jnp.asarray(
                rng.randn(cfg.n_experts, cfg.d_ff, cfg.d_model) /
                np.sqrt(cfg.d_ff), dt)
        else:
            p["w1"] = dense(cfg.d_model, cfg.d_ff)
            p["w2"] = dense(cfg.d_ff, cfg.d_model)
        return p

    out = {
        "embed": jnp.asarray(rng.randn(cfg.vocab_size, cfg.d_model) * 0.02,
                             dt),
        "ln_f": jnp.ones(_norm_shape(cfg), dt),
        "layers": [layer() for _ in range(cfg.n_layers)],
    }
    if not cfg.rope:
        # rope models carry no learned position table — at long-context
        # scale it would be dead HBM (+ momentum + checkpoint bloat)
        out["pos"] = jnp.asarray(
            rng.randn(cfg.max_len, cfg.d_model) * 0.02, dt)
    return out


def shard_params(params, cfg, mesh):
    """device_put every param with its PartitionSpec. Quantized trees
    (quantize_weights_int8) shard too: the int8 payload takes the
    weight's spec, its scale/dt sidecars replicate (scales are shared
    along the leading axis, which no spec here partitions alone)."""
    specs = param_specs(cfg)
    if cfg.tp_axis and cfg.tp_axis in mesh.shape:
        tp_size = mesh.shape[cfg.tp_axis]
        if _kvh(cfg) % tp_size:
            raise ValueError(
                "tp axis of size %d cannot shard %d KV heads "
                "(n_kv_heads must be a multiple of the tp width; "
                "lower tp, raise n_kv_heads, or replicate KV by "
                "setting tp_axis=None)" % (tp_size, _kvh(cfg)))

    def place(x, s):
        if _is_q8(x):
            return {"q8": jax.device_put(x["q8"], NamedSharding(mesh, s)),
                    "scale": jax.device_put(
                        x["scale"], NamedSharding(mesh, P())),
                    "dt": x["dt"]}
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(place, params, specs,
                        is_leaf=lambda x: isinstance(x, P) or _is_q8(x))


def _rms_norm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g


def _qkv(x, p):
    q = jnp.einsum("btd,dhk->bthk", x, p["wq"])
    k = jnp.einsum("btd,dhk->bthk", x, p["wk"])   # KVH heads under GQA
    v = jnp.einsum("btd,dhk->bthk", x, p["wv"])
    return q, k, v


def _flash_min_seq():
    """Sequence-length crossover for the flash-vs-dense dispatch below.

    The only flash-vs-dense chip A/B so far has DENSE winning at
    T=4096 (BENCH_TABLE `flash_attention`: fwd 16.51 ms dense vs 21.92
    flash; fwd+bwd 37.17 vs 44.15), so a config that requests the
    flash kernel still routes short sequences to the dense softmax and
    engages the streamed kernel only where the [T, T] score matrix
    stops fitting the bandwidth budget. 8192 is the first unmeasured
    length above that datapoint ("dense dies past 4k" is a claim, not
    a number — the T>=8192 sweep legs in run_chip_queue.py decide);
    MXNET_FLASH_MIN_SEQ re-pins the crossover when they land."""
    from .. import _fastenv
    try:
        return int(_fastenv.get("MXNET_FLASH_MIN_SEQ", "8192"))
    except (TypeError, ValueError):
        return 8192


def _paged_pallas_requested():
    """MXNET_PAGED_DECODE_PALLAS=1 routes decode_step_paged /
    verify_chunk_paged through the batched-lane Pallas megakernel
    (kernels/paged_decode.py) instead of the fused-gather dense
    contraction. Read at trace time through _fastenv (sub-microsecond,
    monkeypatch-safe) and folded into the _serving_jit key, so an A/B
    harness can flip the flag between arms without stale programs."""
    from .. import _fastenv
    return _fastenv.get("MXNET_PAGED_DECODE_PALLAS", "0") not in (
        "0", "", "false", "False", None)


def _causal_attention(q, k, v, cfg, out_dtype):
    """Single-device causal attention over [B, T, H, D] — flash kernel
    (one block when T fits/divides 128, else gcd(T, 128)-sized blocks,
    so ANY sequence length works) or the dense masked softmax. Shared
    by training forward and prefill. use_flash_kernel is a REQUEST,
    not a route: sequences below the measured crossover
    (MXNET_FLASH_MIN_SEQ, _flash_min_seq above) still take the dense
    path, which the chip A/B has winning there."""
    if cfg.use_flash_kernel and q.shape[1] >= _flash_min_seq():
        from ..kernels import flash_attention
        # block sizing (128 default, MXNET_FLASH_BLOCK_Q/K override,
        # clamp + gcd for short/odd sequences) lives in
        # flash_attention itself — one source of truth
        return flash_attention(q, k, v,
                               causal=True).astype(out_dtype)
    T = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s / np.sqrt(q.shape[-1])
    mask = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    a = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", a,
                      v.astype(a.dtype)).astype(out_dtype)


def _attention(x, p, cfg, mesh, manual_sp=False):
    q, k, v = _qkv(x, p)
    if cfg.rope:
        T = x.shape[1]
        if manual_sp:
            # local shard inside shard_map: global positions start at
            # this device's sequence offset
            start = jax.lax.axis_index(cfg.sp_axis) * T
        else:
            start = 0
        positions = start + jnp.arange(T)
        q = _rope(q, positions, cfg.rope_base)
        k = _rope(k, positions, cfg.rope_base)
    # training paths attend with the repeated view; the MXU cost is the
    # same and every path below assumes matching head counts
    g = cfg.n_heads // _kvh(cfg)
    k, v = _repeat_kv(k, g), _repeat_kv(v, g)
    if manual_sp:
        # already inside a shard_map manual over sp (pipeline stage
        # body). The Pallas path only engages on real TPU: interpret-
        # mode pallas cannot run under this partially-manual shard_map
        # (see ring_attention_sharded); numerics are identical either way
        o = ring_attention(q, k, v, axis_name=cfg.sp_axis, causal=True,
                           use_flash_kernel=cfg.use_flash_kernel
                           and jax.default_backend() == "tpu")
    elif mesh is not None and cfg.use_ring_attention and cfg.sp_axis:
        o = ring_attention_sharded(q, k, v, mesh, axis_name=cfg.sp_axis,
                                   causal=True,
                                   use_flash_kernel=cfg.use_flash_kernel)
    else:
        o = _causal_attention(q, k, v, cfg, x.dtype)
    return jnp.einsum("bthk,hkd->btd", o, p["wo"])


def _ffn(x, p, cfg):
    if cfg.n_experts:
        # dense top-all dispatch: every token weighted over every expert.
        # XLA shards the E dim over ep (and d_ff over tp) so each device
        # computes only its experts' slices; the combine is a psum over ep.
        gates = jax.nn.softmax(
            jnp.einsum("btd,de->bte", x, p["gate"]), axis=-1)
        h = jax.nn.gelu(jnp.einsum("btd,edf->betf", x, p["w1"]))
        y = jnp.einsum("betf,efd->betd", h, p["w2"])
        return jnp.einsum("betd,bte->btd", y, gates)
    h = jax.nn.gelu(jnp.einsum("btd,df->btf", x, p["w1"]))
    return jnp.einsum("btf,fd->btd", h, p["w2"])


def _pp_size(cfg, mesh):
    if mesh is None or not cfg.pp_axis:
        return 1
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(cfg.pp_axis, 1)


def forward(params, tokens, cfg, mesh=None):
    """tokens [B, T] int32 -> logits [B, T, vocab]."""
    x = params["embed"][tokens]
    if not cfg.rope:
        x = x + params["pos"][: tokens.shape[1]]
    act = P(cfg.dp_axis, cfg.sp_axis, None)
    if mesh is not None:
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, act))
    n_stages = _pp_size(cfg, mesh)
    if n_stages > 1:
        # pipeline the homogeneous layer stack over pp: stage-major
        # stacked weights, ppermute microbatch schedule; ring attention
        # runs manually over sp inside each stage, tp/ep stay auto
        ring = bool(cfg.use_ring_attention and cfg.sp_axis)

        def layer_fn(p, xm):
            xm = xm + _attention(_rms_norm(xm, p["ln1"]), p, cfg, mesh,
                                 manual_sp=ring)
            return xm + _ffn(_rms_norm(xm, p["ln2"]), p, cfg)

        if cfg.remat_layers:
            layer_fn = jax.checkpoint(layer_fn)
        stacked = stack_stage_params(params["layers"], n_stages)
        x = spmd_pipeline(
            layer_fn, stacked, x, mesh, axis_name=cfg.pp_axis,
            num_microbatches=cfg.num_microbatches or None,
            extra_manual_axes=(cfg.sp_axis,) if ring else (),
            microbatch_spec=P(None, None, cfg.sp_axis, None) if ring
            else P())
    else:
        def layer_body(p, xl):
            xl = xl + _attention(_rms_norm(xl, p["ln1"]), p, cfg, mesh)
            xl = xl + _ffn(_rms_norm(xl, p["ln2"]), p, cfg)
            if mesh is not None:
                xl = jax.lax.with_sharding_constraint(
                    xl, NamedSharding(mesh, act))
            return xl

        if cfg.remat_layers:
            # save only layer boundaries; backward recomputes each
            # layer's internals (attention scores, ffn hidden) on the fly
            layer_body = jax.checkpoint(layer_body)
        for p in params["layers"]:
            x = layer_body(p, x)
    x = _rms_norm(x, params["ln_f"])
    return jnp.einsum("btd,vd->btv", x, params["embed"])


def loss_fn(params, tokens, cfg, mesh=None):
    """Next-token cross entropy (mean over B, T-1)."""
    # keep the full (sp-divisible) sequence through the model; shift the
    # logits instead of the inputs
    logits = forward(params, tokens, cfg, mesh)[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


# ------------------------------------------------------------- decode ---
# Autoregressive inference: a per-layer KV cache plus a T_q=1 step.
# Prefill could reuse forward(); the same step also serves prefill
# token-by-token, which keeps one compiled program for everything.
# The attention reads ride kernels/flash_attention.flash_decode on TPU
# (cache streamed through VMEM, masked by the dynamic position) and a
# dense masked einsum elsewhere — identical numerics.

def init_cache(cfg, batch):
    """Zeroed per-layer K/V caches sized to cfg.max_len. With
    cfg.kv_cache_int8, each layer holds int8 codes plus per-(batch,
    position, head) fp32 scales ("ks"/"vs") — ~half the HBM of a bf16
    cache (the fp32 scale planes add 4/head_dim of the code bytes:
    ~3% at head_dim 128, but 25% at head_dim 16 — small-head configs
    keep less than the headline half)."""
    hd = cfg.d_model // cfg.n_heads
    shape = (batch, cfg.max_len, _kvh(cfg), hd)
    if cfg.kv_cache_int8:
        sshape = shape[:3]
        return [{"k": jnp.zeros(shape, jnp.int8),
                 "ks": jnp.zeros(sshape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.int8),
                 "vs": jnp.zeros(sshape, jnp.float32)}
                for _ in range(cfg.n_layers)]
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def _kv_quant(x):
    """Symmetric int8 over the last axis: x [..., D] ->
    (codes int8 [..., D], scale fp32 [...])."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _kv_dequant(q8, scale, dtype):
    return (q8.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _cache_write_rows(layer_cache, k, v, start, cfg):
    """Write fresh k/v [B, C, KVH, D] into cache positions
    [start, start+C) — quantizing on the way in under kv_cache_int8."""
    def upd(name, arr):
        return jax.lax.dynamic_update_slice_in_dim(
            layer_cache[name], arr.astype(layer_cache[name].dtype),
            start, axis=1)
    if cfg.kv_cache_int8:
        kq, ks = _kv_quant(k)
        vq, vs = _kv_quant(v)
        return {"k": upd("k", kq), "ks": upd("ks", ks),
                "v": upd("v", vq), "vs": upd("vs", vs)}
    return {"k": upd("k", k), "v": upd("v", v)}


def _int8_cache_attention(qg, layer_cache, mask, out_dtype):
    """The one int8 cache-read contraction (decode is its C=1 case):
    qg [B, C, KVH, G, D] fp against cache codes [B, T, KVH, D] int8.
    mask [B|1, C, T] marks attendable positions. Both products run
    int8 x int8 -> int32 on the MXU; q quantizes per call, k-scales
    multiply the scores per key position, v-scales fold into the
    re-quantized probabilities (they vary along the contraction axis,
    so they must ride the left operand). Every reader — stepped
    decode, chunked prefill, speculative verification — goes through
    THIS function, which is what keeps pool==solo and verify==decode
    bit-identical: the contract is structural, not disciplinary."""
    kq, ks = layer_cache["k"], layer_cache["ks"]
    vq, vs = layer_cache["v"], layer_cache["vs"]
    dh = qg.shape[-1]
    q8, qs = _kv_quant(qg)
    s = jnp.einsum("bckgd,btkd->bckgt", q8, kq,
                   preferred_element_type=jnp.int32).astype(jnp.float32)
    s = s * qs[..., None] * ks.transpose(0, 2, 1)[:, None, :, None, :] \
        / np.sqrt(dh)
    s = jnp.where(mask[:, :, None, None, :], s, -1e30)
    a = jax.nn.softmax(s, axis=-1)
    a8, as_ = _kv_quant(a * vs.transpose(0, 2, 1)[:, None, :, None, :])
    o = jnp.einsum("bckgt,btkd->bckgd", a8, vq,
                   preferred_element_type=jnp.int32).astype(jnp.float32)
    return (o * as_[..., None]).astype(out_dtype)


def _cache_pspec(cfg, x):
    """Serving-cache layout rule in one place (shard_cache and beam's
    traced constraint must agree): batch over dp, heads over tp,
    sequence replicated — truncated to the leaf's rank, because int8
    scale planes are [B, T, KVH] while code planes are rank 4."""
    return P(*P(cfg.dp_axis, None, cfg.tp_axis, None)[: x.ndim])


def _cache_write_ragged(layer_cache, k_new, v_new, pos, cfg):
    """Per-row scatter: row i writes its k/v [B, KVH, D] at pos[i]."""
    rows = jnp.arange(k_new.shape[0])
    def st(name, arr):
        return layer_cache[name].at[rows, pos].set(
            arr.astype(layer_cache[name].dtype))
    if cfg.kv_cache_int8:
        kq, ks = _kv_quant(k_new)
        vq, vs = _kv_quant(v_new)
        return {"k": st("k", kq), "ks": st("ks", ks),
                "v": st("v", vq), "vs": st("vs", vs)}
    return {"k": st("k", k_new), "v": st("v", v_new)}


def quantize_weights_int8(params):
    """Weight-only int8 for serving: every dense >=2-D weight becomes a
    {"q8": int8, "scale": fp32} pair with scales shared only along the
    leading (input) axis — per-output-channel for 2-D weights, finer
    than per-channel for the 3-D head-split ones; 1-D params (norms)
    stay as they are. Decode is HBM-bound on weight reads at small
    batch, so int8 storage halves (vs bf16) or quarters (vs fp32) the
    bytes per token. Under jit (make_decode_step, generate, the jitted
    prefill) XLA fuses the dequantizing convert into each weight's
    consuming matmul, so no full-precision copy is materialized; an
    EAGER decode_step call on a q8 tree dequantizes the whole tree per
    call — serve through the jitted entry points. Idempotent."""
    def q(leaf):
        if _is_q8(leaf):
            return leaf
        if not hasattr(leaf, "ndim") or leaf.ndim < 2:
            return leaf
        x = jnp.asarray(leaf, jnp.float32)
        amax = jnp.max(jnp.abs(x), axis=0, keepdims=True)
        scale = jnp.maximum(amax, 1e-30) / 127.0
        q8 = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        # "dt" is a zero-size carrier of the original dtype — an array
        # leaf (jit-safe) rather than a string
        return {"q8": q8, "scale": scale.astype(jnp.float32),
                "dt": jnp.zeros((0,), leaf.dtype)}
    return jax.tree.map(q, params, is_leaf=_is_q8)


def _is_q8(leaf):
    return isinstance(leaf, dict) and "q8" in leaf


def _dequantize_weights(params):
    """Inverse of quantize_weights_int8, applied INSIDE the compiled
    step — the convert fuses into each weight's consuming matmul."""
    def dq(leaf):
        if _is_q8(leaf):
            return (leaf["q8"].astype(jnp.float32) * leaf["scale"]
                    ).astype(leaf["dt"].dtype)
        return leaf
    return jax.tree.map(dq, params, is_leaf=_is_q8)


def _maybe_dequantize(params):
    return _dequantize_weights(params) \
        if any(_is_q8(l) for l in jax.tree.leaves(
            params, is_leaf=_is_q8)) else params


def shard_cache(cache, cfg, mesh):
    """Lay the KV cache out for mesh-sharded serving: batch over dp,
    heads over tp (matching the wq/wk/wv head shardings), sequence
    replicated — each device holds its heads' full cache and the
    attention needs no cross-device traffic; only wo's output
    contraction all-reduces over tp (GSPMD inserts it)."""
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, _cache_pspec(cfg, x))), cache)


def _decode_attention(q, layer_cache, pos, cfg):
    """q [B,H,D] vs cache [B,Tmax,KVH,D], attending positions <= pos."""
    cache_k, cache_v = layer_cache["k"], layer_cache["v"]
    if cfg.kv_cache_int8:
        return _decode_attention_int8(q, layer_cache, pos, cfg)
    if cfg.use_flash_kernel:
        import math
        from ..kernels import flash_decode
        # largest power-of-two block (<=128) dividing the cache length
        block_k = math.gcd(cache_k.shape[1], 128)
        return flash_decode(q, cache_k, cache_v, pos + 1,
                            block_k=block_k)
    b, h, d = q.shape
    kvh = cache_k.shape[2]
    g = h // kvh
    # grouped contraction: the KVH-head cache is read once per GROUP —
    # no materialized repeat in the bandwidth-bound decode loop.
    # kernels.dense_decode_with_lse is the same contraction with a
    # deliberately different numeric profile: it accumulates PV in
    # fp32 and emits the lse the sequence-parallel shard combine
    # needs; this serving hot loop contracts PV at cache dtype (bf16
    # MXU pass) and needs no lse. A masking/scaling fix here likely
    # applies there too.
    qg = q.reshape(b, kvh, g, d)
    s = jnp.einsum("bkgd,btkd->bkgt", qg, cache_k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    t_pos = jnp.arange(cache_k.shape[1])
    # pos is a scalar (all rows at the same position) or [B] (ragged
    # decode — continuous batching); [1] broadcasts the scalar case
    mask = t_pos[None, :] <= jnp.atleast_1d(pos)[:, None]
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    a = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgt,btkd->bkgd", a.astype(cache_v.dtype), cache_v,
                   preferred_element_type=jnp.float32)
    return o.reshape(b, h, d).astype(q.dtype)


def _decode_attention_int8(q, layer_cache, pos, cfg):
    """Decode = the C=1 case of _int8_cache_attention (nothing
    dequantized is ever materialized in HBM: the cache streams at
    int8 width, which is the point)."""
    b, h, d = q.shape
    kvh = layer_cache["k"].shape[2]
    t_pos = jnp.arange(layer_cache["k"].shape[1])
    mask = (t_pos[None, :] <= jnp.atleast_1d(pos)[:, None])[:, None, :]
    o = _int8_cache_attention(
        q.reshape(b, 1, kvh, h // kvh, d), layer_cache, mask, q.dtype)
    return o.reshape(b, h, d)


def prefill(params, cache, tokens, cfg):
    """Process the whole prompt in ONE forward pass, filling the KV
    cache for positions [0, Tp) — the serving-side complement of the
    per-token decode_step (prompt cost: one batched MXU pass instead of
    Tp tiny ones). Shares the q/k/v projection and causal-attention
    block with the training forward (_qkv/_causal_attention); ring
    (sp-sharded) attention is a training-path feature prefill does not
    engage. Returns (last_logits [B, vocab], cache)."""
    if cfg.kv_cache_int8:
        # delegate to the chunked path: its attention reads the prompt
        # rows THROUGH the quantizer, exactly as decode later will —
        # keeping solo generate() and the continuous batcher's
        # admission (which prefills via prefill_chunk) bit-identical
        return prefill_chunk(params, cache, tokens, jnp.int32(0), cfg,
                             logits_row=jnp.int32(tokens.shape[1] - 1),
                             attend_limit=int(tokens.shape[1]))
    params = _maybe_dequantize(params)
    b, t_p = tokens.shape
    x = params["embed"][tokens]
    if not cfg.rope:
        x = x + params["pos"][:t_p]
    new_cache = []
    for p, layer_cache in zip(params["layers"], cache):
        h = _rms_norm(x, p["ln1"])
        q, k, v = _qkv(h, p)
        if cfg.rope:
            # keys are cached ROTATED: their rotation depends only on
            # their own position, so decode never re-rotates the cache
            positions = jnp.arange(t_p)
            q = _rope(q, positions, cfg.rope_base)
            k = _rope(k, positions, cfg.rope_base)
        new_cache.append(_cache_write_rows(layer_cache, k, v, 0, cfg))
        g = cfg.n_heads // _kvh(cfg)
        o = _causal_attention(q, _repeat_kv(k, g), _repeat_kv(v, g),
                              cfg, x.dtype)
        x = x + jnp.einsum("bthk,hkd->btd", o, p["wo"])
        x = x + _ffn(_rms_norm(x, p["ln2"]), p, cfg)
    x = _rms_norm(x[:, -1], params["ln_f"])
    return jnp.einsum("bd,vd->bv", x, params["embed"]), new_cache


# jitted prefill per config VALUE: generate() is the latency-sensitive
# serving convenience, and re-wrapping jit per call would retrace every
# request. Content keying means a mutated config retraces (no stale
# program) and fresh-but-equal configs share one entry; the LRU bound
# keeps a long-lived server from accumulating dead compiles.
_PREFILL_JIT_CACHE = {}
_PREFILL_JIT_LIMIT = 32


def _serving_jit(kind, cfg, build):
    import dataclasses
    # the backend is part of the key: builders bake backend-dependent
    # choices (e.g. _serving_donate's donation tuple) into the wrapper,
    # so a process that pins a different backend after warming must not
    # reuse a stale wrapper
    # the paged-kernel flag is trace-time env state the builders bake
    # in, so it keys too: a bench toggling MXNET_PAGED_DECODE_PALLAS
    # between arms must get two programs, not one stale one
    key = (kind, jax.default_backend(),
           _paged_pallas_requested()) + dataclasses.astuple(cfg)
    fn = _PREFILL_JIT_CACHE.pop(key, None)
    if fn is None:
        frozen = dataclasses.replace(cfg)   # defensive copy: later
        # mutations of the caller's cfg must not leak into the trace
        fn = build(frozen)
    _PREFILL_JIT_CACHE[key] = fn            # re-insert = move to back
    while len(_PREFILL_JIT_CACHE) > _PREFILL_JIT_LIMIT:
        _PREFILL_JIT_CACHE.pop(next(iter(_PREFILL_JIT_CACHE)))
    return fn


def _serving_donate(*argnums):
    """Donation tuple for a serving entry point's device-resident state
    (KV cache, and the pipelined batcher's tok/pos/keys carry): saves
    one HBM copy per donated arg on accelerators; the CPU backend can't
    donate and would warn on every call."""
    return () if jax.default_backend() == "cpu" else argnums


def _jitted_prefill(cfg):
    return _serving_jit("prefill", cfg, lambda fz: jax.jit(
        lambda p, c, t: prefill(p, c, t, fz)))


def _jitted_prefill_chunk(cfg):
    # chunk width is a shape, so jax.jit re-specializes per width and
    # caches each; `start` stays dynamic (dynamic_slice inside)
    return _serving_jit("prefill_chunk", cfg, lambda fz: jax.jit(
        lambda p, c, t, s: prefill_chunk(p, c, t, s, fz)))


def _jitted_prefill_chunk_row(cfg):
    # admission variant: logits for ONE chunk row — skips the
    # O(width*vocab) head projection the caller would throw away
    return _serving_jit("prefill_chunk_row", cfg, lambda fz: jax.jit(
        lambda p, c, t, s, r: prefill_chunk(p, c, t, s, fz,
                                            logits_row=r)))


def _jitted_decode_step(cfg):
    return _serving_jit("decode_step", cfg, lambda fz: jax.jit(
        lambda p, c, t, pos: decode_step(p, c, t, pos, fz)))


def prefill_chunk(params, cache, tokens, start, cfg, logits_row=None,
                  attend_limit=None):
    """Process a CHUNK of C tokens beginning at dynamic position
    `start`, writing their K/V into the cache and returning the logits
    after every chunk position ([B, C, vocab]) — or, with
    `logits_row` (dynamic scalar), only that row's logits [B, vocab]:
    the admission path of continuous batching needs one row and skips
    the O(C*vocab) head projection.

    `attend_limit` (STATIC int) restricts the attention contraction to
    the first `attend_limit` cache positions — exact (the mask zeroes
    the tail anyway) whenever the caller knows start+C <= limit, e.g.
    the whole-prompt prefill at start=0, which otherwise pays a
    max_len-wide score matrix for a prompt-wide prompt.

    The chunked middle ground between prefill (whole prompt at 0) and
    decode_step (one token): long prompts stream through in fixed-size
    chunks, and speculative decoding verifies k draft tokens in one
    pass. Row i of the chunk attends cache positions <= start+i, so
    stale cache entries beyond the verified stream are never read (and
    are overwritten when re-processed)."""
    params = _maybe_dequantize(params)
    b, c = tokens.shape
    try:
        concrete_end = int(start) + c      # eager path only; traced
    except Exception:                      # starts check inside jit is
        concrete_end = None                # the caller's contract
    if concrete_end is not None and concrete_end > cfg.max_len:
        raise ValueError(
            "chunk [%d, %d) overruns max_len %d (dynamic_update_slice "
            "would clamp and corrupt earlier cache positions)"
            % (concrete_end - c, concrete_end, cfg.max_len))
    x = params["embed"][tokens]
    if cfg.rope:
        chunk_pos = start + jnp.arange(c)
    else:
        x = x + jax.lax.dynamic_slice_in_dim(params["pos"], start, c, 0)
    new_cache = []
    g = cfg.n_heads // _kvh(cfg)
    for p, layer_cache in zip(params["layers"], cache):
        h = _rms_norm(x, p["ln1"])
        q, k, v = _qkv(h, p)
        if cfg.rope:
            q = _rope(q, chunk_pos, cfg.rope_base)
            k = _rope(k, chunk_pos, cfg.rope_base)
        nlayer = _cache_write_rows(layer_cache, k, v, start, cfg)
        new_cache.append(nlayer)
        # chunk row i sees cache positions <= start+i; grouped
        # contraction reads the KVH-head cache once per GROUP (like
        # _decode_attention — no materialized repeat on the hot path)
        dh = q.shape[-1]
        qg = q.reshape(b, c, _kvh(cfg), g, dh)
        att = nlayer if attend_limit is None else \
            {name: arr[:, :attend_limit] for name, arr in nlayer.items()}
        t_pos = jnp.arange(att["k"].shape[1])
        mask = (t_pos[None, :]
                <= (start + jnp.arange(c))[:, None])[None]   # [1,C,T]
        if cfg.kv_cache_int8:
            o = _int8_cache_attention(qg, att, mask, x.dtype) \
                .reshape(b, c, cfg.n_heads, dh)
        else:
            ck, cv = att["k"], att["v"]
            s = jnp.einsum("bckgd,btkd->bckgt", qg, ck,
                           preferred_element_type=jnp.float32
                           ) / np.sqrt(dh)
            s = jnp.where(mask[:, :, None, None, :], s, -1e30)
            a = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bckgt,btkd->bckgd", a.astype(cv.dtype), cv,
                           preferred_element_type=jnp.float32
                           ).astype(x.dtype).reshape(b, c,
                                                     cfg.n_heads, dh)
        x = x + jnp.einsum("bchk,hkd->bcd", o, p["wo"])
        x = x + _ffn(_rms_norm(x, p["ln2"]), p, cfg)
    x = _rms_norm(x, params["ln_f"])
    if logits_row is not None:
        xr = jax.lax.dynamic_index_in_dim(x, logits_row, 1,
                                          keepdims=False)
        return jnp.einsum("bd,vd->bv", xr, params["embed"]), new_cache
    return jnp.einsum("bcd,vd->bcv", x, params["embed"]), new_cache


def _spec_core(params, draft_params, prompt, cfg, dcfg, k, n_new):
    """The whole speculative generation as ONE traceable program:
    prefill both models, then a lax.while_loop of rounds — draft scan
    (k small-model steps), one big-model verify chunk, device-side
    acceptance and a masked window write into the token buffer. The
    loop runs entirely on device; the host syncs once, on the result.

    Acceptance math: drafts agree with the big model's argmax `target`
    on a leading prefix; since drafts[i] == target[i] inside it, the
    round's emissions are simply target[:accepted+1] (the +1 being the
    corrected/bonus token), clamped to the remaining budget.

    `n_new` is TRACED (the loop bound is data): one compiled program
    serves every budget at a given prompt length — buffers size by
    cfg.max_len, the caller slices. Varying n_new costs nothing;
    only a new prompt length (or a k re-clamp near max_len)
    re-specializes, like any jit shape."""
    t_prompt = prompt.shape[1]
    total = t_prompt + n_new
    cache = init_cache(cfg, 1)
    dcache = init_cache(dcfg, 1)
    logits, cache = prefill(params, cache, prompt, cfg)
    _, dcache = prefill(draft_params, dcache, prompt, dcfg)
    # pad the buffer so the fixed-width (k+1) window write near the
    # budget edge stays in bounds; emissions beyond `total` are masked
    buf = jnp.zeros((cfg.max_len + k + 1,), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt[0], (0,))
    buf = buf.at[t_prompt].set(
        jnp.argmax(logits[0]).astype(jnp.int32))
    acc_log = jnp.zeros((cfg.max_len,), jnp.int32)  # >= 1 token/round

    def cond(state):
        return state[0] < total

    def body(state):
        n, buf, cache, dcache, acc_log, rounds = state
        tok0 = jax.lax.dynamic_slice(buf, (n - 1,), (1,))

        def dbody(carry, i):
            tok, dc = carry
            dlogits, dc = decode_step(draft_params, dc, tok,
                                      n - 1 + i, dcfg)
            nxt = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
            return (nxt, dc), nxt[0]

        (_, dcache), drafts = jax.lax.scan(
            dbody, (tok0, dcache), jnp.arange(k))
        # one big-model pass verifies all k proposals: the k+1 chunk
        # rows are the contexts ending at buf[n-1], d1, ..., d_k, so
        # row i predicts position n+i (row k is the bonus after a full
        # acceptance)
        window = jnp.concatenate([tok0, drafts])[None]
        vlogits, cache2 = prefill_chunk(params, cache, window,
                                        n - 1, cfg)
        target = jnp.argmax(vlogits[0], axis=-1).astype(jnp.int32)
        accepted = jnp.cumprod(
            (drafts == target[:k]).astype(jnp.int32)).sum()
        emit = jnp.minimum(accepted + 1, total - n)
        old = jax.lax.dynamic_slice(buf, (n,), (k + 1,))
        new = jnp.where(jnp.arange(k + 1) < emit, target, old)
        buf = jax.lax.dynamic_update_slice(buf, new, (n,))
        acc_log = acc_log.at[rounds].set(accepted)
        return (n + emit, buf, cache2, dcache, acc_log, rounds + 1)

    state = (jnp.int32(t_prompt + 1), buf, cache, dcache, acc_log,
             jnp.int32(0))
    n, buf, _, _, acc_log, rounds = jax.lax.while_loop(cond, body,
                                                       state)
    return buf[None], acc_log, rounds


def speculative_generate(params, draft_params, prompt, n_new, cfg,
                         draft_cfg, k_draft=4, return_stats=False):
    """Greedy speculative decoding: a small DRAFT model proposes
    k_draft tokens per round, the big model verifies them all in ONE
    prefill_chunk pass, and the longest agreeing prefix is accepted
    (plus the big model's corrected/bonus token). Every emitted token
    is the big model's greedy argmax — identical to generate() up to
    floating-point reduction-order ties between the chunked and
    per-token attention paths (argmax gaps below kernel noise, ~1e-6,
    can tip either way; any well-separated argmax matches exactly).
    Batch size 1 (acceptance length is data-dependent per row).
    Returns [1, Tp+n_new] int32 (with return_stats=True, also a dict
    of per-round acceptance counts and big-model launch count).

    The whole generation — both prefills and every draft/verify
    round — compiles to ONE device program (_spec_core), dispatched
    once: rounds advance in a lax.while_loop with the acceptance test
    on device, so tokens/s is bounded by model compute, not by
    host-loop round trips (which dominate when the accelerator sits
    behind a network tunnel). The round count and per-round window
    width k are fixed at trace time; near the budget edge extra
    emissions are masked rather than re-shaped, and k is clamped so
    the fixed-width draft/verify writes stay inside both caches
    (cache writes beyond the verified stream self-heal: attention
    masks by position, and rejected-draft entries are overwritten by
    the next round before they become attendable).

    Both configs must share vocab_size."""
    if prompt.shape[0] != 1:
        raise ValueError("speculative decoding serves batch=1")
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share the vocab")
    t_prompt = int(prompt.shape[1])
    total = t_prompt + n_new
    if total > min(cfg.max_len, draft_cfg.max_len):
        raise ValueError("prompt+n_new exceeds a model's max_len")
    if n_new < 1:
        raise ValueError("n_new must be >= 1")
    # deepest in-round write is position n-1+k with n <= total-1; keep
    # it inside BOTH caches (k_draft degrades gracefully near max_len)
    k = max(1, min(int(k_draft),
                   cfg.max_len - total + 1,
                   draft_cfg.max_len - total + 1))
    import dataclasses
    dfrozen = dataclasses.replace(draft_cfg)   # freeze like _serving_jit
    fn = _serving_jit(
        ("speculative", k, dataclasses.astuple(draft_cfg)), cfg,
        lambda fz: jax.jit(
            lambda p, dp, t, n: _spec_core(p, dp, t, fz, dfrozen,
                                           k, n)))
    out, acc_log, rounds = fn(params, draft_params, prompt,
                              jnp.int32(n_new))
    out = out[:, :total]          # host-side: n_new is data in-program
    if return_stats:
        rounds = int(rounds)
        return out, {"acceptances": [int(a) for a in
                                     np.asarray(acc_log)[:rounds]],
                     "big_model_launches": 1 + rounds}
    return out


def decode_step(params, cache, tokens, pos, cfg):
    """One autoregressive step.

    tokens [B] int32 (the token at position `pos`), pos scalar int32 —
    or int32 [B] for RAGGED decode (each row at its own position; what
    continuous batching needs, see models/serving.py). Returns
    (logits [B, vocab] for the NEXT token, updated cache).
    Static shapes throughout: `pos` is data, not shape, so one compiled
    program decodes every position. Accepts quantize_weights_int8
    trees: the dequantizing converts fuse into each weight's matmul.
    """
    params = _maybe_dequantize(params)
    ragged = jnp.ndim(pos) == 1        # trace-time branch: [B] vs scalar
    x = params["embed"][tokens]
    if not cfg.rope:
        if ragged:
            x = x + jnp.take(params["pos"], pos, axis=0)
        else:
            x = x + jax.lax.dynamic_index_in_dim(
                params["pos"], pos, 0, keepdims=False)
    b = x.shape[0]
    new_cache = []
    for p, layer_cache in zip(params["layers"], cache):
        h = _rms_norm(x, p["ln1"])
        q = jnp.einsum("bd,dhk->bhk", h, p["wq"])
        k_new = jnp.einsum("bd,dhk->bhk", h, p["wk"])
        v_new = jnp.einsum("bd,dhk->bhk", h, p["wv"])
        if cfg.rope:
            q = _rope(q, pos, cfg.rope_base)
            k_new = _rope(k_new, pos, cfg.rope_base)
        if ragged:
            # per-row scatter: row i writes its K/V at its own pos[i]
            nlayer = _cache_write_ragged(layer_cache, k_new, v_new,
                                         pos, cfg)
        else:
            nlayer = _cache_write_rows(layer_cache, k_new[:, None],
                                       v_new[:, None], pos, cfg)
        new_cache.append(nlayer)
        o = _decode_attention(q, nlayer, pos, cfg)
        x = x + jnp.einsum("bhk,hkd->bd", o, p["wo"])
        x = x + _ffn(_rms_norm(x, p["ln2"])[:, None], p, cfg)[:, 0]
    x = _rms_norm(x, params["ln_f"])
    return jnp.einsum("bd,vd->bv", x, params["embed"]), new_cache


# ------------------------------------------------------- paged decode ---
# The KV cache virtualized into fixed-size BLOCKS: one per-layer pool
# `[num_blocks, block_size, KVH, Dh]` shared by every lane, plus per-lane
# int32 block TABLES `[B, max_len // block_size]` mapping position range
# [j*bs, (j+1)*bs) to a pool block. Capacity decouples from max_len — a
# lane holds exactly the blocks its context needs, and a block mapped
# into two tables (shared prefix) is stored once. Block 0 is the
# reserved NULL block: unallocated table entries point at it, so decode
# writes past a lane's allocation land in a shared garbage sink (never
# attendable for a live request — attention masks to <= pos, and the
# allocator covers every live position with a real block) instead of
# corrupting a neighbour. Reads gather the pool through the table into
# the dense [B, T] layout and reuse the SAME attention contractions as
# the dense cache (_decode_attention and its int8/GQA/flash variants):
# the gathered view carries bit-identical values at every unmasked
# position, which is what keeps paged == dense == solo generate()
# bit-exact rather than approximately equal. Allocation policy (free
# list, refcounts, copy-on-extend sharing) lives in models/serving.py —
# this layer is purely the compiled read/write geometry.

def init_paged_cache(cfg, num_blocks, block_size):
    """Zeroed per-layer block pools. Layout matches init_cache with the
    position axis split into [num_blocks, block_size]; under
    kv_cache_int8 the per-(position, head) fp32 scale planes split the
    same way ([num_blocks, block_size, KVH]), so a block carries its
    own scales and int8-KV composes per block."""
    if num_blocks < 2:
        raise ValueError("need >= 2 blocks (block 0 is the null block)")
    hd = cfg.d_model // cfg.n_heads
    shape = (num_blocks, block_size, _kvh(cfg), hd)
    if cfg.kv_cache_int8:
        sshape = shape[:3]
        return [{"k": jnp.zeros(shape, jnp.int8),
                 "ks": jnp.zeros(sshape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.int8),
                 "vs": jnp.zeros(sshape, jnp.float32)}
                for _ in range(cfg.n_layers)]
    return [{"k": jnp.zeros(shape, cfg.dtype),
             "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def paged_cache_nbytes(cfg, num_blocks, block_size):
    """Analytic byte size of the pool :func:`init_paged_cache` would
    build — mirrors its dtype geometry (int8 k/v + fp32 scale planes
    under kv_cache_int8, else ``cfg.dtype``) without allocating. The
    memory budget's preflight for pool init/grow reads this."""
    hd = cfg.d_model // cfg.n_heads
    cells = num_blocks * block_size * _kvh(cfg)
    if cfg.kv_cache_int8:
        per_layer = 2 * cells * hd * 1 + 2 * cells * 4   # k/v + ks/vs
    else:
        per_layer = 2 * cells * hd * jnp.dtype(cfg.dtype).itemsize
    return int(per_layer * cfg.n_layers)


def grow_paged_cache(pool, extra_blocks):
    """The pool with ``extra_blocks`` fresh zero blocks appended to
    every leaf's block axis. Existing blocks keep their ids and values
    (a pure concat — no copy of live data semantics change), so block
    tables remain valid and the allocator simply extends its free list
    with the new ids."""
    if extra_blocks <= 0:
        return pool
    def g(leaf):
        pad = jnp.zeros((extra_blocks,) + leaf.shape[1:], leaf.dtype)
        return jnp.concatenate([leaf, pad], axis=0)
    return [{name: g(leaf) for name, leaf in layer.items()}
            for layer in pool]


def _paged_gather(layer_pool, tables):
    """Gather one layer's pool through the block tables into the dense
    [B, NB*bs, ...] cache layout — ONE fused XLA gather feeding the
    same attention contraction as the dense path (no Pallas). Table
    entry j covers positions [j*bs, (j+1)*bs), so the flattened axis is
    in position order and the `<= pos` mask applies unchanged."""
    b, nb = tables.shape
    flat = tables.reshape(-1)

    def g(leaf):
        got = jnp.take(leaf, flat, axis=0)        # [B*NB, bs, ...]
        return got.reshape((b, nb * leaf.shape[1]) + leaf.shape[2:])

    return {name: g(leaf) for name, leaf in layer_pool.items()}


def _paged_write_ragged(layer_pool, k_new, v_new, tables, pos, cfg):
    """Per-row scatter through the table: row i writes its k/v
    [B, KVH, D] into block tables[i, pos[i]//bs] at offset pos[i]%bs —
    quantizing on the way in under kv_cache_int8, like the dense
    ragged write. A position past the table (a retired lane coasting
    to its chunk boundary) clamps to the last entry, which the
    allocator guarantees is never a shared block; an unallocated entry
    is the null block. Either way the garbage is unreadable."""
    bs = layer_pool["k"].shape[1]
    blk = jnp.take_along_axis(tables, (pos // bs)[:, None], axis=1)[:, 0]
    off = pos % bs

    def st(name, arr):
        return layer_pool[name].at[blk, off].set(
            arr.astype(layer_pool[name].dtype))

    if cfg.kv_cache_int8:
        kq, ks = _kv_quant(k_new)
        vq, vs = _kv_quant(v_new)
        return {"k": st("k", kq), "ks": st("ks", ks),
                "v": st("v", vq), "vs": st("vs", vs)}
    return {"k": st("k", k_new), "v": st("v", v_new)}


def decode_step_paged(params, pool, tables, tokens, pos, cfg):
    """One ragged autoregressive step through the block tables.

    tokens [B] int32, pos [B] int32 (always ragged — this is the
    continuous-batching entry point), tables [B, max_len//bs] int32.
    Returns (logits [B, vocab], updated pool); the tables themselves
    are read-only here — allocation is the host scheduler's job.
    Everything the dense step supports composes: RoPE (keys cached
    rotated), GQA (the gathered view keeps KVH heads; the grouped
    contraction reads each once per group), int8-KV (codes + per-block
    scales gathered together, the one shared _int8_cache_attention
    does the rest), quantized weight trees."""
    params = _maybe_dequantize(params)
    x = params["embed"][tokens]
    if not cfg.rope:
        x = x + jnp.take(params["pos"], pos, axis=0)
    new_pool = []
    for p, layer_pool in zip(params["layers"], pool):
        h = _rms_norm(x, p["ln1"])
        q = jnp.einsum("bd,dhk->bhk", h, p["wq"])
        k_new = jnp.einsum("bd,dhk->bhk", h, p["wk"])
        v_new = jnp.einsum("bd,dhk->bhk", h, p["wv"])
        if cfg.rope:
            q = _rope(q, pos, cfg.rope_base)
            k_new = _rope(k_new, pos, cfg.rope_base)
        nlayer = _paged_write_ragged(layer_pool, k_new, v_new, tables,
                                     pos, cfg)
        new_pool.append(nlayer)
        if _paged_pallas_requested():
            # batched-lane megakernel: reads the pool THROUGH the
            # tables (no dense gather copy), skips dead blocks per
            # lane. The batcher's membudget preflight already covers
            # this jit boundary (it preflights every dispatch fn), and
            # the scope makes its bytes attributable via hlo/attribution.
            from ..kernels import paged_attention
            from ..observability import attribution as _obs_attr
            _obs_attr.note_scope("paged_decode_kernel")
            with jax.named_scope("paged_decode_kernel"):
                o = paged_attention(q[:, None], nlayer, tables,
                                    pos)[:, 0]
        else:
            o = _decode_attention(q, _paged_gather(nlayer, tables),
                                  pos, cfg)
        x = x + jnp.einsum("bhk,hkd->bd", o, p["wo"])
        x = x + _ffn(_rms_norm(x, p["ln2"])[:, None], p, cfg)[:, 0]
    x = _rms_norm(x, params["ln_f"])
    return jnp.einsum("bd,vd->bv", x, params["embed"]), new_pool


# ------------------------------------------------------ batched verify ---
# The ragged-chunk forward that batched speculative decoding needs: C
# tokens per lane, each lane's window anchored at its OWN position. Both
# variants share the attention contractions with prefill_chunk (dense /
# _int8_cache_attention), which is what keeps batched verify bit-exact
# with the stepped decode it replaces.

def _cache_write_ragged_chunk(layer_cache, k_new, v_new, positions, cfg):
    """Per-row WINDOW scatter: row b writes its C fresh k/v
    [B, C, KVH, D] at its own positions[b, :] — the C>1 generalization
    of _cache_write_ragged. Out-of-bounds positions (a lane's window
    running past max_len) are DROPPED by the scatter rather than
    clamped, so a deep window can never corrupt an earlier,
    still-attendable cache row."""
    rows = jnp.arange(k_new.shape[0])[:, None]

    def st(name, arr):
        return layer_cache[name].at[rows, positions].set(
            arr.astype(layer_cache[name].dtype), mode="drop")

    if cfg.kv_cache_int8:
        kq, ks = _kv_quant(k_new)
        vq, vs = _kv_quant(v_new)
        return {"k": st("k", kq), "ks": st("ks", ks),
                "v": st("v", vq), "vs": st("vs", vs)}
    return {"k": st("k", k_new), "v": st("v", v_new)}


def verify_chunk(params, cache, tokens, pos, cfg):
    """Process a RAGGED chunk: C tokens PER LANE, lane b's window
    starting at its own position pos[b] ([B] int32 — data, not shape,
    like every serving entry point). Row (b, i) carries the stream
    token at position pos[b]+i, writes its K/V there, attends cache
    positions <= pos[b]+i, and its logits predict position pos[b]+i+1.
    This is the batched generalization of prefill_chunk (whose `start`
    is one scalar for the whole batch) and the target pass of batched
    speculative decoding: the [B, k+1] window [tok, d_1..d_k] yields
    every lane's verification targets in ONE dispatch.

    Stale K/V from rejected drafts heals by position exactly as the
    solo _spec_core documents: the next round's window starts at the
    first rejected position and rewrites every stale position before
    any row can attend it. Windows that run past max_len (a parked
    lane, a near-budget lane coasting) DROP their writes instead of
    clamping. Returns (logits [B, C, vocab], cache)."""
    params = _maybe_dequantize(params)
    b, c = tokens.shape
    positions = pos[:, None] + jnp.arange(c)[None, :]        # [B, C]
    x = params["embed"][tokens]
    if not cfg.rope:
        # take() clamps OOB rows — their logits are garbage, but their
        # writes drop and their emissions are never credited
        x = x + jnp.take(params["pos"], positions, axis=0)
    new_cache = []
    g = cfg.n_heads // _kvh(cfg)
    for p, layer_cache in zip(params["layers"], cache):
        h = _rms_norm(x, p["ln1"])
        q, k, v = _qkv(h, p)
        if cfg.rope:
            q = _rope(q, positions, cfg.rope_base)
            k = _rope(k, positions, cfg.rope_base)
        nlayer = _cache_write_ragged_chunk(layer_cache, k, v,
                                           positions, cfg)
        new_cache.append(nlayer)
        dh = q.shape[-1]
        qg = q.reshape(b, c, _kvh(cfg), g, dh)
        t_pos = jnp.arange(nlayer["k"].shape[1])
        mask = t_pos[None, None, :] <= positions[:, :, None]  # [B,C,T]
        if cfg.kv_cache_int8:
            o = _int8_cache_attention(qg, nlayer, mask, x.dtype) \
                .reshape(b, c, cfg.n_heads, dh)
        else:
            ck, cv = nlayer["k"], nlayer["v"]
            s = jnp.einsum("bckgd,btkd->bckgt", qg, ck,
                           preferred_element_type=jnp.float32
                           ) / np.sqrt(dh)
            s = jnp.where(mask[:, :, None, None, :], s, -1e30)
            a = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bckgt,btkd->bckgd", a.astype(cv.dtype), cv,
                           preferred_element_type=jnp.float32
                           ).astype(x.dtype).reshape(b, c,
                                                     cfg.n_heads, dh)
        x = x + jnp.einsum("bchk,hkd->bcd", o, p["wo"])
        x = x + _ffn(_rms_norm(x, p["ln2"]), p, cfg)
    x = _rms_norm(x, params["ln_f"])
    return jnp.einsum("bcd,vd->bcv", x, params["embed"]), new_cache


def _paged_write_ragged_chunk(layer_pool, k_new, v_new, tables,
                              positions, cfg):
    """Window scatter through the block tables: row b writes its C
    fresh k/v at positions[b, :], each position routed to block
    tables[b, position//bs] at offset position%bs. Positions past the
    TABLE (beyond max_len) are routed to the null block — unlike the
    single-position _paged_write_ragged, clamping to the last entry is
    not safe here, because a near-budget lane's window can overrun
    while the lane is still live and its last block still attendable.
    Unallocated entries are the null block as usual."""
    bs = layer_pool["k"].shape[1]
    nb = tables.shape[1]
    blk_idx = positions // bs                                # [B, C]
    blk = jnp.take_along_axis(tables, jnp.clip(blk_idx, 0, nb - 1),
                              axis=1)
    blk = jnp.where(blk_idx < nb, blk, 0)
    off = positions % bs

    def st(name, arr):
        return layer_pool[name].at[blk, off].set(
            arr.astype(layer_pool[name].dtype))

    if cfg.kv_cache_int8:
        kq, ks = _kv_quant(k_new)
        vq, vs = _kv_quant(v_new)
        return {"k": st("k", kq), "ks": st("ks", ks),
                "v": st("v", vq), "vs": st("vs", vs)}
    return {"k": st("k", k_new), "v": st("v", v_new)}


def verify_chunk_paged(params, pool, tables, tokens, pos, cfg):
    """verify_chunk through the block tables: same ragged-window
    semantics, writes scattered into the pool
    (_paged_write_ragged_chunk), reads through the gathered dense view
    (_paged_gather) into the SAME attention contraction as the dense
    verify — bit-identical values at every unmasked position, so
    paged == dense == solo stays exact under speculation. Tables are
    read-only here; allocation (including the speculative over-reserve
    and release-on-reject) is the host scheduler's job.
    Returns (logits [B, C, vocab], pool)."""
    params = _maybe_dequantize(params)
    b, c = tokens.shape
    positions = pos[:, None] + jnp.arange(c)[None, :]        # [B, C]
    x = params["embed"][tokens]
    if not cfg.rope:
        x = x + jnp.take(params["pos"], positions, axis=0)
    new_pool = []
    g = cfg.n_heads // _kvh(cfg)
    for p, layer_pool in zip(params["layers"], pool):
        h = _rms_norm(x, p["ln1"])
        q, k, v = _qkv(h, p)
        if cfg.rope:
            q = _rope(q, positions, cfg.rope_base)
            k = _rope(k, positions, cfg.rope_base)
        nlayer = _paged_write_ragged_chunk(layer_pool, k, v, tables,
                                           positions, cfg)
        new_pool.append(nlayer)
        dh = q.shape[-1]
        if _paged_pallas_requested():
            # same megakernel, span=C: the ragged [B, k+1] spec-verify
            # window is just the k>1 case of the decode grid
            from ..kernels import paged_attention
            from ..observability import attribution as _obs_attr
            _obs_attr.note_scope("paged_verify_kernel")
            with jax.named_scope("paged_verify_kernel"):
                o = paged_attention(q, nlayer, tables, pos)
            x = x + jnp.einsum("bchk,hkd->bcd", o, p["wo"])
            x = x + _ffn(_rms_norm(x, p["ln2"]), p, cfg)
            continue
        qg = q.reshape(b, c, _kvh(cfg), g, dh)
        att = _paged_gather(nlayer, tables)
        t_pos = jnp.arange(att["k"].shape[1])
        mask = t_pos[None, None, :] <= positions[:, :, None]  # [B,C,T]
        if cfg.kv_cache_int8:
            o = _int8_cache_attention(qg, att, mask, x.dtype) \
                .reshape(b, c, cfg.n_heads, dh)
        else:
            ck, cv = att["k"], att["v"]
            s = jnp.einsum("bckgd,btkd->bckgt", qg, ck,
                           preferred_element_type=jnp.float32
                           ) / np.sqrt(dh)
            s = jnp.where(mask[:, :, None, None, :], s, -1e30)
            a = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bckgt,btkd->bckgd", a.astype(cv.dtype), cv,
                           preferred_element_type=jnp.float32
                           ).astype(x.dtype).reshape(b, c,
                                                     cfg.n_heads, dh)
        x = x + jnp.einsum("bchk,hkd->bcd", o, p["wo"])
        x = x + _ffn(_rms_norm(x, p["ln2"]), p, cfg)
    x = _rms_norm(x, params["ln_f"])
    return jnp.einsum("bcd,vd->bcv", x, params["embed"]), new_pool


def make_decode_step(cfg):
    """Jitted decode_step with the cache donated (updated in place)."""
    def step(params, cache, tokens, pos):
        return decode_step(params, cache, tokens, pos, cfg)
    return jax.jit(step, donate_argnums=(1,))


def _sample_logits(logits, key, temperature, top_k, top_p):
    """One sampling step over [B, V] logits — temperature scaling,
    static top-k truncation, and nucleus (top-p) filtering, all
    jit-compatible (static shapes; masking instead of gathering)."""
    logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
    if top_k is not None and 0 < top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep every token whose PRECEDING cumulative mass < top_p (the
        # first token is always kept)
        keep_sorted = jnp.concatenate(
            [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < top_p],
            axis=-1)
        # threshold logit = smallest kept logit per row
        thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


def _generate_core(params, prompt, cache, key, n_new, cfg, greedy,
                   temperature, top_k, top_p):
    """prefill + decode scan, one traceable program (see generate)."""
    b, t_prompt = prompt.shape
    total = t_prompt + n_new
    buf = jnp.zeros((b, total), jnp.int32).at[:, :t_prompt].set(prompt)

    def choose(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), key
        key, sub = jax.random.split(key)
        return _sample_logits(logits, sub, temperature, top_k,
                              top_p), key

    last_logits, cache = prefill(params, cache, prompt, cfg)
    nxt, key = choose(last_logits, key)
    buf = buf.at[:, t_prompt].set(nxt)

    def body(carry, pos):
        buf, cache, key = carry
        tok = jax.lax.dynamic_index_in_dim(buf, pos, 1, keepdims=False)
        logits, cache = decode_step(params, cache, tok, pos, cfg)
        nxt, key = choose(logits, key)
        buf = jax.lax.dynamic_update_slice_in_dim(
            buf, nxt[:, None], pos + 1, axis=1)
        return (buf, cache, key), None

    if n_new > 1:
        (buf, _, _), _ = jax.lax.scan(
            body, (buf, cache, key),
            jnp.arange(t_prompt, total - 1))
    return buf


def generate(params, prompt, n_new, cfg, greedy=None, seed=0,
             temperature=1.0, top_k=None, top_p=None, mesh=None):
    """Autoregressive generation: prompt [B, Tp] int32 -> [B, Tp+n_new].

    Sampling: by default, passing any of `temperature` (!= 1.0),
    `top_k`, or `top_p` samples with those controls; otherwise decoding
    is greedy argmax. Passing greedy=True together with sampling
    controls is a contradiction and raises. With `mesh`, the KV cache
    is laid out dp/tp-sharded (shard_cache) to match TP-sharded params.
    The prompt is prefilled in ONE batched forward (prefill), then the
    generation steps run as one lax.scan.

    Both the mesh-sharded and single-device calls run as ONE cached
    jitted program (keyed on cfg + the sampling controls;
    n_new/prompt-length/input-sharding re-specialize like any shape) —
    repeated generate() calls pay zero re-trace, which is what a
    serving loop needs (benchmark/serving_bench.py measures this).
    """
    sampling_requested = (temperature != 1.0 or top_k is not None
                          or top_p is not None)
    if greedy is None:
        greedy = not sampling_requested
    elif greedy and sampling_requested:
        raise ValueError(
            "greedy=True ignores temperature/top_k/top_p — pass "
            "greedy=False (or omit greedy) to sample")
    b, t_prompt = prompt.shape
    total = t_prompt + n_new
    if total > cfg.max_len:
        raise ValueError("prompt+n_new %d exceeds max_len %d"
                         % (total, cfg.max_len))
    if n_new == 0:
        return prompt
    cache = init_cache(cfg, b)
    if mesh is not None:
        # jit specializes per input sharding, so the sharded and
        # single-device calls share one cached wrapper
        cache = shard_cache(cache, cfg, mesh)
    key = jax.random.PRNGKey(seed)
    fn = _serving_jit(
        ("generate", bool(greedy), float(temperature), top_k, top_p),
        cfg,
        lambda fz: jax.jit(
            lambda p, t, c, k, n: _generate_core(
                p, t, c, k, n, fz, greedy, temperature, top_k, top_p),
            static_argnums=(4,), donate_argnums=_serving_donate(2)))
    return fn(params, prompt, cache, key, n_new)


def beam_search(params, prompt, n_new, cfg, beam=4, length_penalty=0.0,
                mesh=None):
    """Beam-search decoding over the KV cache: prompt [B, Tp] ->
    (sequences [B, beam, Tp+n_new], scores [B, beam]), beams sorted
    best-first by total log-probability (optionally length-normalized
    by (Tp+n_new)^length_penalty).

    The cache rides at batch width B*beam; each step re-gathers the
    cache rows of the surviving beams' parents (a batched take inside
    the scan — static shapes, one compiled program for the loop).
    beam=1 reduces exactly to greedy generate(). Quantized trees pass
    through (dequant fuses inside the compiled steps); with `mesh`,
    the expanded cache is laid out dp/tp-sharded like generate()'s."""
    b, t_prompt = prompt.shape
    total = t_prompt + n_new
    if total > cfg.max_len:
        raise ValueError("prompt+n_new %d exceeds max_len %d"
                         % (total, cfg.max_len))
    if n_new < 1:
        raise ValueError("beam search needs n_new >= 1")
    if not 1 <= beam <= cfg.vocab_size:
        raise ValueError("beam width %d must be in [1, vocab_size=%d]"
                         % (beam, cfg.vocab_size))
    k = beam

    cache = init_cache(cfg, b)
    if mesh is not None:
        cache = shard_cache(cache, cfg, mesh)
    # one cached jitted program per (cfg, beam, penalty, mesh) — like
    # generate(), repeated beam_search() calls pay zero re-trace
    fn = _serving_jit(
        ("beam", k, float(length_penalty), mesh), cfg,
        lambda fz: jax.jit(
            lambda p, t, c, n: _beam_core(p, t, c, n, k,
                                          length_penalty, fz, mesh),
            static_argnums=(3,), donate_argnums=_serving_donate(2)))
    return fn(params, prompt, cache, n_new)


def _beam_core(params, prompt, cache, n_new, k, length_penalty, cfg,
               mesh):
    """prefill + beam expansion + decode scan, one traceable program
    (see beam_search)."""
    b, t_prompt = prompt.shape
    total = t_prompt + n_new
    vocab = cfg.vocab_size
    last_logits, cache = prefill(params, cache, prompt, cfg)
    logp0 = jax.nn.log_softmax(last_logits.astype(jnp.float32), axis=-1)

    # first expansion: top-k tokens of the last prompt position seed
    # the beams; the cache is replicated per beam (rows grouped as
    # [b0*k beams..., b1*k beams, ...])
    scores, tok0 = jax.lax.top_k(logp0, k)            # [B, k]
    rep = lambda x: jnp.repeat(x, k, axis=0)
    cache = jax.tree.map(rep, cache)
    if mesh is not None:
        # traced equivalent of shard_cache for the beam-expanded rows
        cache = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, _cache_pspec(cfg, x))), cache)
    buf = jnp.zeros((b * k, total), jnp.int32)
    buf = buf.at[:, :t_prompt].set(jnp.repeat(prompt, k, axis=0))
    buf = buf.at[:, t_prompt].set(tok0.reshape(-1))

    def body(carry, pos):
        buf, cache, scores = carry
        tok = jax.lax.dynamic_index_in_dim(buf, pos, 1, keepdims=False)
        logits, cache = decode_step(params, cache, tok, pos, cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        cand = scores.reshape(b, k, 1) + logp.reshape(b, k, vocab)
        scores, flat = jax.lax.top_k(cand.reshape(b, k * vocab), k)
        parent = flat // vocab                         # [B, k]
        token = (flat % vocab).astype(jnp.int32)
        # re-gather the surviving parents' rows
        row = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
        cache = jax.tree.map(lambda x: jnp.take(x, row, axis=0), cache)
        buf = jnp.take(buf, row, axis=0)
        buf = jax.lax.dynamic_update_slice_in_dim(
            buf, token.reshape(-1, 1), pos + 1, axis=1)
        return (buf, cache, scores), None

    if n_new > 1:
        (buf, _, scores), _ = jax.lax.scan(
            body, (buf, cache, scores),
            jnp.arange(t_prompt, total - 1))
    if length_penalty:
        scores = scores / (float(total) ** length_penalty)
    # beams emerge sorted (top_k order is descending)
    return buf.reshape(b, k, total), scores


def make_train_step(cfg, mesh=None, lr=1e-2, guard=False):
    """Jitted full training step: (params, opt_state, tokens) ->
    (params, opt_state, loss). SGD with momentum, all-reduce of grads is
    implicit in GSPMD (grads inherit param shardings).

    With ``guard=True`` the step returns a fourth output ``skipped``
    (device bool) and applies the NON-FINITE STEP GUARD entirely on
    device: if the loss or any gradient is NaN/Inf, params and momentum
    pass through untouched — one divergent batch can never poison the
    weights, and an uninterrupted guarded run stays bit-identical to
    the unguarded one as long as nothing trips (the selects choose the
    same updated arrays)."""

    def step(params, momentum, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)
        new_m = jax.tree.map(lambda m, g: 0.9 * m + g, momentum, grads)
        new_p = jax.tree.map(lambda p, m: p - lr * m.astype(p.dtype),
                             params, new_m)
        if not guard:
            return new_p, new_m, loss
        ok = jnp.isfinite(loss)
        for g in jax.tree.leaves(grads):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
        params = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                              new_p, params)
        momentum = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                                new_m, momentum)
        return params, momentum, loss, jnp.logical_not(ok)

    return jax.jit(step, donate_argnums=(0, 1))


def init_momentum(params):
    return jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params)


# sharded checkpoint/resume for this stack lives in models/checkpoint.py;
# re-exported here so the flagship's whole train/serve/persist surface is
# reachable from one module
from .checkpoint import (save_checkpoint, load_checkpoint,  # noqa: E402
                         restore_train_state, resume_from_latest,
                         CheckpointCorrupt, wait_for_pending_save,
                         install_emergency_checkpoint,
                         uninstall_emergency_checkpoint)
