"""Model families implemented TPU-first.

`transformer` is the SPMD flagship for multi-chip execution (dp/tp/sp/ep
sharded training step over a jax.sharding.Mesh); the classic CNN families
live in `mxnet_tpu.gluon.model_zoo.vision` behind the MXNet Gluon API.
"""

from . import transformer
from . import checkpoint
from . import journal
from . import serving
from . import router
