"""Continuous batching for autoregressive serving.

A fixed pool of B cache slots decodes as ONE ragged batch (each row at
its own position — `decode_step` with vector `pos`); requests are
admitted into free slots mid-stream and leave when done, so the batch
never drains to refill (the reference serves Module.predict batch-at-
a-time: `/root/reference/python/mxnet/module/base_module.py:336-420`;
continuous batching is the TPU-serving upgrade of that surface —
static shapes, one compiled step program, no pipeline bubbles between
requests).

Design notes (all static-shape, XLA-friendly):

* One compiled ragged decode step serves every mix of positions — pos
  is data, not shape.
* Admission prefills the prompt at a power-of-two BUCKET width (one
  compiled prefill per bucket, not per prompt length) with the logits
  row for the true last token selected out. Pad garbage in the cache
  beyond the prompt is harmless: attention masks to `<= pos`, and
  positions beyond the prompt are overwritten by decode writes before
  they ever become attendable — the same self-healing argument the
  speculative decoder relies on.
* Idle slots keep lanes busy writing at position 0 of retired rows;
  the next admission's prefill overwrites them. Throughput is
  proportional to active lanes, latency to the slowest active row —
  exactly the continuous-batching trade.
* Chunk PIPELINING (pipeline_depth >= 2): the decode carry — cache,
  per-lane tokens/positions, sample keys — stays device-resident, so
  chunk k+1 dispatches against chunk k's output buffers before anyone
  syncs chunk k's emissions, and the host round trip (the ~15 ms
  tunnel RTT that capped the round-5 serving leg at 252 tok/s)
  amortizes over `depth` chunks. Admission/eviction are jitted lane
  patches sequenced after the in-flight chunks; emissions are credited
  by dispatch-time lane identity, which is what keeps every stream
  bit-identical to the synchronous pool and to solo generate().

* PAGED KV cache (paged=True / MXNET_KV_PAGED): the per-lane dense
  [max_len] cache rows become one per-layer block pool + per-lane int32
  block tables (tf.init_paged_cache / tf.decode_step_paged — reads are
  a fused gather into the same dense contraction, so streams stay
  bit-exact). Admission accounts in BLOCKS against a refcounting
  free-list allocator: capacity = pool blocks, not lanes x max_len,
  blocks allocate lazily as positions advance (against an
  admission-time reservation) and free on finish/evict, and
  cache_prefix becomes refcounted block SHARING (full prefix blocks
  stored once, copy-on-extend for partial tails, freed at refcount
  zero). Composes with int8-KV (quantized pool + per-block scales),
  GQA, chunking, pipelining (the carry holds pool + tables), and the
  dispatch-failure requeue path.

* SPECULATIVE dispatches (spec_k / MXNET_SPEC_K): every decode round
  drafts k tokens per lane — from a small draft model or, by default,
  n-gram prompt-lookup against the lane's own stream — then verifies
  all lanes' [k+1] windows in ONE ragged target pass
  (tf.verify_chunk / verify_chunk_paged) with device-side cumprod
  acceptance, so the accepted prefix + one free token land per lane
  per dispatch (1..k+1 tokens instead of exactly 1). Rejected cache
  writes heal by position (`attention <= pos`, as everywhere above);
  paged block tables advance by ACCEPTED counts with worst-case draft
  blocks released at sync; pipelining keeps depth speculative
  dispatches in flight; a per-lane adaptive-k controller
  (MXNET_SPEC_ACCEPT_FLOOR) shrinks the draft width where measured
  acceptance is poor. Greedy-only, and bit-exact vs solo generate()
  — the accept test IS the target argmax.

Greedy decoding (the serving default); sampling per-row is a
straightforward extension (thread a per-slot PRNG key through step()).
Weight-only int8 trees (quantize_weights_int8) pass through unchanged.
"""

import dataclasses
import json
import os
import time
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from . import journal as _journal
from . import transformer as tf
from .. import _fastenv
from ..observability import attribution as _attr
from ..observability import chaos as _chaos
from ..observability import core as _obs
from ..observability import events as _events
from ..observability import flight as _flight
from ..observability import http as _obs_http
from ..observability import recompile as _obs_recompile
from ..observability import timeseries as _timeseries
from ..observability import integrity as _integrity
from ..observability import membudget as _membudget
from ..observability import slo as _slo

DEFAULT_KV_BLOCK_SIZE = 16


def _bucket(n, lo=8):
    b = lo
    while b < n:
        b *= 2
    return b


def _jitted_ragged_step(cfg, greedy, temperature, top_k, top_p):
    """One compiled program: ragged decode + per-row token choice.

    Sampling mirrors generate()'s key chain PER ROW (split the row's
    key, sample with the sub-key), so a request's sampled stream is
    identical to its solo generate(seed=...) run — slot placement and
    pool mix cannot perturb it."""
    def build(fz):
        def step(params, cache, tok, pos, keys):
            logits, cache = tf.decode_step(params, cache, tok, pos, fz)
            if greedy:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, keys, cache
            split = jax.vmap(jax.random.split)(keys)   # [B, 2, 2]
            keys, subs = split[:, 0], split[:, 1]
            nxt = jax.vmap(
                lambda l, k: tf._sample_logits(
                    l[None], k, temperature, top_k, top_p)[0]
            )(logits, subs)
            return nxt, keys, cache
        return jax.jit(step, donate_argnums=tf._serving_donate(1))
    return tf._serving_jit(
        ("decode_ragged", greedy, float(temperature), top_k, top_p),
        cfg, build)


def _jitted_ragged_chunk(cfg, greedy, temperature, top_k, top_p, k):
    """`k` ragged decode steps as ONE compiled program (lax.scan) —
    multi-step scheduling. Each host round trip costs a dispatch plus
    a result sync; when the chip sits behind a network tunnel that
    latency (~tens of ms) dwarfs a decode step, so stepping once per
    token caps the pool at ~1/RTT tokens per lane. Scanning k steps
    on device amortizes the round trip k-fold; the host applies the
    [k, B] token block afterwards, discarding any tail a request
    emitted past its stop token or budget (bounded waste, the
    standard continuous-batching trade for chunked scheduling)."""
    def build(fz):
        def chunk(params, cache, tok, pos, keys):
            def body(carry, _):
                cache, tok, pos, keys = carry
                logits, cache = tf.decode_step(params, cache, tok,
                                               pos, fz)
                if greedy:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    split = jax.vmap(jax.random.split)(keys)
                    keys, subs = split[:, 0], split[:, 1]
                    nxt = jax.vmap(
                        lambda l, kk: tf._sample_logits(
                            l[None], kk, temperature, top_k, top_p)[0]
                    )(logits, subs)
                return (cache, nxt, pos + 1, keys), nxt
            (cache, _, _, keys), toks = jax.lax.scan(
                body, (cache, tok, pos, keys), None, length=k)
            return toks, keys, cache           # toks [k, B]
        return jax.jit(chunk, donate_argnums=tf._serving_donate(1))
    return tf._serving_jit(
        ("decode_ragged_chunk", greedy, float(temperature), top_k,
         top_p, k), cfg, build)


def _jitted_pipeline_chunk(cfg, greedy, temperature, top_k, top_p, k):
    """`k` ragged decode steps that return the WHOLE rolling carry
    (cache, last token, advanced positions, key chain) alongside the
    [k, B] emissions — the dispatch unit of the PIPELINED batcher.

    The sync-mode chunk (_jitted_ragged_chunk) hands its carry back to
    the host, which re-uploads it next step; here the carry never
    leaves the device, so chunk k+1 can be dispatched against chunk
    k's output buffers BEFORE anyone syncs chunk k's tokens. The
    emissions are the only output the host ever fetches. The carry is
    donated on accelerators (tok/pos/keys included — they are dead the
    moment the next chunk is built from them)."""
    def build(fz):
        def chunk(params, cache, tok, pos, keys):
            def body(carry, _):
                cache, tok, pos, keys = carry
                logits, cache = tf.decode_step(params, cache, tok,
                                               pos, fz)
                if greedy:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    split = jax.vmap(jax.random.split)(keys)
                    keys, subs = split[:, 0], split[:, 1]
                    nxt = jax.vmap(
                        lambda l, kk: tf._sample_logits(
                            l[None], kk, temperature, top_k, top_p)[0]
                    )(logits, subs)
                return (cache, nxt, pos + 1, keys), nxt
            (cache, tok, pos, keys), toks = jax.lax.scan(
                body, (cache, tok, pos, keys), None, length=k)
            return toks, cache, tok, pos, keys   # toks [k, B]
        return jax.jit(chunk,
                       donate_argnums=tf._serving_donate(1, 2, 3, 4))
    return tf._serving_jit(
        ("decode_pipeline", greedy, float(temperature), top_k, top_p,
         k), cfg, build)


def _jitted_lane_patch(cfg):
    """Patch ONE lane of the device-resident (tok, pos, keys) carry —
    the admission / lane-clear primitive of the pipelined batcher.
    Runs as a tiny device program sequenced after whatever chunks are
    in flight (it consumes the last dispatch's output buffers), so a
    freed or freshly-admitted lane takes effect exactly at the next
    dispatch boundary, with no host round trip."""
    return tf._serving_jit("lane_patch", cfg, lambda fz: jax.jit(
        lambda tok, pos, keys, i, t, p, key: (
            tok.at[i].set(t), pos.at[i].set(p), keys.at[i].set(key)),
        donate_argnums=tf._serving_donate(0, 1, 2)))


def _jitted_admit_token(cfg, greedy, temperature, top_k, top_p):
    """First generated token from the prefill logits, chosen ON
    DEVICE: argmax under greedy, else generate()'s exact key chain
    (key = PRNGKey(seed); split once; sample with the sub-key; carry
    the key). The pipelined admit() pulls only this SCALAR to the
    host — not the [vocab] logits row — and the returned key patches
    straight into the key-chain carry."""
    def build(fz):
        def pick(last, seed):
            if greedy:
                return (jnp.argmax(last).astype(jnp.int32),
                        jnp.zeros((2,), jnp.uint32))
            key = jax.random.PRNGKey(seed)
            key, sub = jax.random.split(key)
            first = tf._sample_logits(last[None], sub, temperature,
                                      top_k, top_p)[0]
            return first, jnp.asarray(key, jnp.uint32)
        return jax.jit(pick)
    return tf._serving_jit(
        ("admit_token", greedy, float(temperature), top_k, top_p),
        cfg, build)


def _jitted_slot_write(cfg):
    """Write a 1-row prefilled cache into slot `i` of the pool cache.

    The copy is deliberately FULL-ROW ([1, max_len] per layer, not the
    prompt's bucket width): it clears the previous occupant's K/V
    beyond the bucket, which is load-bearing for slot reuse — any
    future narrowing to bucket width must add an explicit tail-clear
    or retired requests' cache lines become attendable again once the
    new request decodes past its own prompt."""
    return tf._serving_jit("slot_write", cfg, lambda fz: jax.jit(
        lambda full, row, i: jax.tree.map(
            lambda f, r: jax.lax.dynamic_update_slice_in_dim(
                f, r.astype(f.dtype), i, axis=0), full, row),
        donate_argnums=tf._serving_donate(0)))


# ---- paged-cache compiled programs -------------------------------------
# Ragged decode through the per-layer block pool + per-lane block tables
# (tf.decode_step_paged): same scheduling shapes as the dense programs
# with the cache argument split into (pool, tables). The pool is donated
# like the dense cache; tables are donated only by the pipelined chunk
# (which carries them device-resident) — the sync programs read them.

def _jitted_ragged_step_paged(cfg, greedy, temperature, top_k, top_p):
    def build(fz):
        def step(params, pool, tables, tok, pos, keys):
            logits, pool = tf.decode_step_paged(params, pool, tables,
                                                tok, pos, fz)
            if greedy:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, keys, pool
            split = jax.vmap(jax.random.split)(keys)
            keys, subs = split[:, 0], split[:, 1]
            nxt = jax.vmap(
                lambda l, k: tf._sample_logits(
                    l[None], k, temperature, top_k, top_p)[0]
            )(logits, subs)
            return nxt, keys, pool
        return jax.jit(step, donate_argnums=tf._serving_donate(1))
    return tf._serving_jit(
        ("decode_ragged_paged", greedy, float(temperature), top_k,
         top_p), cfg, build)


def _jitted_ragged_chunk_paged(cfg, greedy, temperature, top_k, top_p,
                               k):
    def build(fz):
        def chunk(params, pool, tables, tok, pos, keys):
            def body(carry, _):
                pool, tok, pos, keys = carry
                logits, pool = tf.decode_step_paged(
                    params, pool, tables, tok, pos, fz)
                if greedy:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    split = jax.vmap(jax.random.split)(keys)
                    keys, subs = split[:, 0], split[:, 1]
                    nxt = jax.vmap(
                        lambda l, kk: tf._sample_logits(
                            l[None], kk, temperature, top_k, top_p)[0]
                    )(logits, subs)
                return (pool, nxt, pos + 1, keys), nxt
            (pool, _, _, keys), toks = jax.lax.scan(
                body, (pool, tok, pos, keys), None, length=k)
            return toks, keys, pool            # toks [k, B]
        return jax.jit(chunk, donate_argnums=tf._serving_donate(1))
    return tf._serving_jit(
        ("decode_ragged_chunk_paged", greedy, float(temperature),
         top_k, top_p, k), cfg, build)


def _jitted_pipeline_chunk_paged(cfg, greedy, temperature, top_k,
                                 top_p, k):
    """Paged twin of _jitted_pipeline_chunk: the rolling carry is
    (pool, tables, tok, pos, keys), all device-resident and donated —
    tables pass through unchanged (allocation patches apply between
    dispatches, host-side)."""
    def build(fz):
        def chunk(params, pool, tables, tok, pos, keys):
            def body(carry, _):
                pool, tok, pos, keys = carry
                logits, pool = tf.decode_step_paged(
                    params, pool, tables, tok, pos, fz)
                if greedy:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    split = jax.vmap(jax.random.split)(keys)
                    keys, subs = split[:, 0], split[:, 1]
                    nxt = jax.vmap(
                        lambda l, kk: tf._sample_logits(
                            l[None], kk, temperature, top_k, top_p)[0]
                    )(logits, subs)
                return (pool, nxt, pos + 1, keys), nxt
            (pool, tok, pos, keys), toks = jax.lax.scan(
                body, (pool, tok, pos, keys), None, length=k)
            return toks, pool, tables, tok, pos, keys
        return jax.jit(chunk,
                       donate_argnums=tf._serving_donate(1, 2, 3, 4, 5))
    return tf._serving_jit(
        ("decode_pipeline_paged", greedy, float(temperature), top_k,
         top_p, k), cfg, build)


def _jitted_block_write(cfg, n):
    """Scatter `n` consecutive blocks of a [1, max_len] row cache
    (positions [start, start + n*bs)) into pool blocks `ids` — the
    paged admission's slot-write: only the NON-SHARED tail of a prompt
    is ever written, whole blocks at a time (so a freed-and-reallocated
    block is completely overwritten, no tail-clear needed)."""
    def build(fz):
        def wr(pool, row, ids, start):
            def leaf(pleaf, rleaf):
                bs = pleaf.shape[1]
                sl = jax.lax.dynamic_slice_in_dim(
                    rleaf.astype(pleaf.dtype), start, n * bs, axis=1)
                return pleaf.at[ids].set(
                    sl.reshape((n, bs) + pleaf.shape[2:]))
            return [{name: leaf(pl[name], rl[name]) for name in pl}
                    for pl, rl in zip(pool, row)]
        return jax.jit(wr, donate_argnums=tf._serving_donate(0))
    return tf._serving_jit(("paged_block_write", n), cfg, build)


def _jitted_gather_row(cfg, nb):
    """Gather `nb` pool blocks into a fresh [1, max_len] row cache
    (zero beyond nb*bs) — the admission-side prefix materialization:
    the suffix prefill attends over the shared prefix through this
    row, while the shared blocks themselves stay untouched in the
    pool."""
    def build(fz):
        def ga(pool, ids):
            def leaf(pleaf):
                bs = pleaf.shape[1]
                got = jnp.take(pleaf, ids, axis=0)
                got = got.reshape((1, nb * bs) + pleaf.shape[2:])
                full = jnp.zeros((1, fz.max_len) + pleaf.shape[2:],
                                 pleaf.dtype)
                return full.at[:, : nb * bs].set(got)
            return [{name: leaf(pl[name]) for name in pl}
                    for pl in pool]
        return jax.jit(ga)
    return tf._serving_jit(("paged_gather_row", nb), cfg, build)


def _jitted_table_row(cfg):
    """Replace lane i's whole block-table row (admission / park)."""
    return tf._serving_jit("paged_table_row", cfg, lambda fz: jax.jit(
        lambda tb, i, row: tb.at[i].set(row),
        donate_argnums=tf._serving_donate(0)))


def _jitted_table_entry(cfg):
    """Point one table entry at a freshly allocated block (the lazy
    per-dispatch extension)."""
    return tf._serving_jit("paged_table_entry", cfg, lambda fz: jax.jit(
        lambda tb, i, j, bid: tb.at[i, j].set(bid),
        donate_argnums=tf._serving_donate(0)))


# ---- speculative-decoding compiled programs ----------------------------
# Batched draft/verify/accept: each round proposes k tokens per lane,
# verifies every lane's [k+1] window in ONE ragged target pass
# (tf.verify_chunk / verify_chunk_paged), and rolls each lane forward by
# its own accepted count — per-lane acceptance is the _spec_core cumprod
# prefix-match, computed on device. Rejected cache entries heal by
# position exactly as the solo path documents (the next window starts at
# the first rejected position and rewrites everything it will attend).

# smoothing of the per-lane measured-acceptance EWMA the adaptive-k
# controller compares against MXNET_SPEC_ACCEPT_FLOOR
_SPEC_EWMA_ALPHA = 0.3


def _ngram_propose(hist, tok, pos, keff, k, ng):
    """Prompt-lookup self-drafting (device-side, static-shape): for
    each lane, find the LATEST earlier occurrence of the ng-token
    suffix ending at the lane's current token and propose the k tokens
    that followed it — drawn from the lane's OWN stream history
    (`hist[b, :pos[b]+1]` is prompt + emissions, `hist[b, pos[b]] ==
    tok[b]`). No second model; repetitive text (code, quoted context,
    templated output) is where it pays. Lanes with no match, or a
    match whose continuation runs off the known stream, fall back to
    repeating the current token (right on runs, rejected otherwise —
    never a correctness question, the verify pass decides every
    emission). Draft slots at or past keff[b] are masked to the -1
    sentinel, which no vocab id equals — that is how the per-lane
    adaptive k shrinks the effective draft length inside one
    static-width program."""
    b, hl = hist.shape
    j = jnp.arange(hl)
    sidx = jnp.clip(pos[:, None] - (ng - 1) + jnp.arange(ng)[None],
                    0, hl - 1)
    suffix = jnp.take_along_axis(hist, sidx, axis=1)         # [B, ng]
    m = jnp.ones((b, hl), bool)
    for o in range(ng):                    # ng is tiny and static
        m = m & (jnp.roll(hist, -o, axis=1) == suffix[:, o:o + 1])
    # a candidate must END strictly before the suffix's own end — this
    # both excludes the trivial self-match and keeps roll()'s
    # wrap-around columns out of range
    valid = (j[None, :] + ng - 1) < pos[:, None]
    best = jnp.max(jnp.where(m & valid, j[None, :], -1), axis=1)
    gidx = best[:, None] + ng + jnp.arange(k)[None]          # [B, k]
    cand = jnp.take_along_axis(hist, jnp.clip(gidx, 0, hl - 1), axis=1)
    usable = (best[:, None] >= 0) & (gidx <= pos[:, None])
    drafts = jnp.where(usable, cand, tok[:, None])
    return jnp.where(jnp.arange(k)[None] < keff[:, None], drafts, -1)


def _jitted_spec_chunk(cfg, dcfg, k, ng, rounds, paged, use_model):
    """`rounds` speculative rounds as ONE compiled program — the
    dispatch unit of the speculative batcher, shaped like the
    pipelined chunk so the same in-flight window applies: the carry
    (cache/pool [+ draft cache/pool or n-gram history], lane tokens,
    positions) stays device-resident and is donated; the only outputs
    the host ever fetches are the per-round verified targets
    [rounds, B, k+1] and emit counts [rounds, B] (emit = accepted + 1:
    the verify logits always yield one token beyond the accepted
    prefix, so every round advances every lane — speculation can never
    be slower than stepping in tokens per dispatch). Greedy only; the
    batcher enforces that at construction."""
    kk = k + 1

    def build(fz):
        def accept(drafts, target):
            # _spec_core's acceptance, batched: count the matching
            # draft prefix per lane, emit it plus the one free token,
            # and the lane's new current token is target[acc]
            acc = jnp.cumprod(
                (drafts == target[:, :k]).astype(jnp.int32),
                axis=1).sum(axis=1)
            emit = acc + 1
            tok = jnp.take_along_axis(target, acc[:, None],
                                      axis=1)[:, 0]
            return emit, tok

        def hist_update(hist, target, emit, pos):
            # masked lane-buffer write: only the ACCEPTED window
            # prefix enters the stream history (positions past
            # max_len, and rejected slots, drop)
            rows = jnp.arange(hist.shape[0])[:, None]
            hpos = pos[:, None] + 1 + jnp.arange(kk)[None]
            keep = jnp.arange(kk)[None] < emit[:, None]
            safe = jnp.where(keep, hpos, fz.max_len + kk)
            return hist.at[rows, safe].set(target, mode="drop")

        if not use_model and not paged:
            def chunk(params, cache, hist, tok, pos, keff):
                def body(carry, _):
                    cache, hist, tok, pos = carry
                    drafts = _ngram_propose(hist, tok, pos, keff, k, ng)
                    window = jnp.concatenate(
                        [tok[:, None], jnp.maximum(drafts, 0)], axis=1)
                    logits, cache = tf.verify_chunk(
                        params, cache, window, pos, fz)
                    target = jnp.argmax(logits, axis=-1) \
                        .astype(jnp.int32)
                    emit, tok = accept(drafts, target)
                    hist = hist_update(hist, target, emit, pos)
                    return (cache, hist, tok, pos + emit), \
                        (target, emit)
                (cache, hist, tok, pos), (targets, emits) = \
                    jax.lax.scan(body, (cache, hist, tok, pos), None,
                                 length=rounds)
                return targets, emits, cache, hist, tok, pos
            donate = tf._serving_donate(1, 2, 3, 4)
        elif not use_model:
            def chunk(params, pool, tables, hist, tok, pos, keff):
                def body(carry, _):
                    pool, hist, tok, pos = carry
                    drafts = _ngram_propose(hist, tok, pos, keff, k, ng)
                    window = jnp.concatenate(
                        [tok[:, None], jnp.maximum(drafts, 0)], axis=1)
                    logits, pool = tf.verify_chunk_paged(
                        params, pool, tables, window, pos, fz)
                    target = jnp.argmax(logits, axis=-1) \
                        .astype(jnp.int32)
                    emit, tok = accept(drafts, target)
                    hist = hist_update(hist, target, emit, pos)
                    return (pool, hist, tok, pos + emit), \
                        (target, emit)
                (pool, hist, tok, pos), (targets, emits) = \
                    jax.lax.scan(body, (pool, hist, tok, pos), None,
                                 length=rounds)
                return targets, emits, pool, hist, tok, pos
            donate = tf._serving_donate(1, 3, 4, 5)
        elif not paged:
            def chunk(params, dparams, cache, dcache, tok, pos, keff):
                def body(carry, _):
                    cache, dcache, tok, pos = carry
                    def dstep(c, i):
                        dc, t = c
                        dl, dc = tf.decode_step(dparams, dc, t,
                                                pos + i, dcfg)
                        nxt = jnp.argmax(dl, axis=-1) \
                            .astype(jnp.int32)
                        return (dc, nxt), nxt
                    (dcache, _), seq = jax.lax.scan(
                        dstep, (dcache, tok), jnp.arange(k))
                    drafts = jnp.where(
                        jnp.arange(k)[None] < keff[:, None],
                        seq.T, -1)
                    window = jnp.concatenate(
                        [tok[:, None], jnp.maximum(drafts, 0)], axis=1)
                    logits, cache = tf.verify_chunk(
                        params, cache, window, pos, fz)
                    target = jnp.argmax(logits, axis=-1) \
                        .astype(jnp.int32)
                    emit, tok = accept(drafts, target)
                    return (cache, dcache, tok, pos + emit), \
                        (target, emit)
                (cache, dcache, tok, pos), (targets, emits) = \
                    jax.lax.scan(body, (cache, dcache, tok, pos), None,
                                 length=rounds)
                return targets, emits, cache, dcache, tok, pos
            donate = tf._serving_donate(2, 3, 4, 5)
        else:
            def chunk(params, dparams, pool, dpool, tables, tok, pos,
                      keff):
                def body(carry, _):
                    pool, dpool, tok, pos = carry
                    def dstep(c, i):
                        dc, t = c
                        dl, dc = tf.decode_step_paged(
                            dparams, dc, tables, t, pos + i, dcfg)
                        nxt = jnp.argmax(dl, axis=-1) \
                            .astype(jnp.int32)
                        return (dc, nxt), nxt
                    (dpool, _), seq = jax.lax.scan(
                        dstep, (dpool, tok), jnp.arange(k))
                    drafts = jnp.where(
                        jnp.arange(k)[None] < keff[:, None],
                        seq.T, -1)
                    window = jnp.concatenate(
                        [tok[:, None], jnp.maximum(drafts, 0)], axis=1)
                    logits, pool = tf.verify_chunk_paged(
                        params, pool, tables, window, pos, fz)
                    target = jnp.argmax(logits, axis=-1) \
                        .astype(jnp.int32)
                    emit, tok = accept(drafts, target)
                    return (pool, dpool, tok, pos + emit), \
                        (target, emit)
                (pool, dpool, tok, pos), (targets, emits) = \
                    jax.lax.scan(body, (pool, dpool, tok, pos), None,
                                 length=rounds)
                return targets, emits, pool, dpool, tok, pos
            donate = tf._serving_donate(2, 3, 5, 6)
        return jax.jit(chunk, donate_argnums=donate)

    key = ("spec_chunk", k, ng, rounds, paged, use_model,
           dataclasses.astuple(dcfg) if use_model else None)
    return tf._serving_jit(key, cfg, build)


def _jitted_hist_row(cfg):
    """Replace lane i's stream-history row (the n-gram drafting state)
    at admission/requeue — the hist twin of the lane patch, sequenced
    after the in-flight dispatches like every carry patch."""
    return tf._serving_jit("spec_hist_row", cfg, lambda fz: jax.jit(
        lambda h, i, row: h.at[i].set(row),
        donate_argnums=tf._serving_donate(0)))


class BlockAllocator(object):
    """Free-list allocator with per-block refcounts over the paged KV
    pool. Block 0 is the reserved null block (unallocated table entries
    point at it) and is never handed out. A block mapped into several
    tables (shared prefix) carries one reference per mapping — prefix
    cache entry included — and returns to the free list only at
    refcount zero, so evicting one sharer can never free a block a
    live lane still reads.

    ``reserved`` tracks the worst-case FUTURE block demand of admitted
    requests: admission reserves its whole lifetime up front (that is
    the block-accounted capacity check), the lazy per-dispatch
    allocation converts reservation into real blocks as positions
    advance, and ``available`` (free minus reserved) is what admission
    and the router may still promise. A live request can therefore
    never stall on an empty free list.

    Under memory pressure the pool is ELASTIC (ISSUE 14):
    :meth:`shrink` moves free blocks onto a parked ledger — out of
    circulation, never below what ``reserved`` has already promised —
    and :meth:`grow` returns them; :meth:`extend` adds physically new
    block ids after the batcher grew the device pool. Parked blocks
    stay in the conservation law (pool == free + referenced + parked,
    the "reserved-aware" identity ``check_invariants`` asserts after
    every shrink/grow cycle)."""

    __slots__ = ("num_blocks", "ref", "reserved", "_free", "_parked")

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is null)")
        self.num_blocks = int(num_blocks)
        # pop() hands out low ids first
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self.ref = np.zeros((self.num_blocks,), np.int32)
        self.reserved = 0
        self._parked = []     # blocks taken out of circulation (shrink)

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def available(self):
        return len(self._free) - self.reserved

    @property
    def parked_blocks(self):
        return len(self._parked)

    def shrink(self, n):
        """Park up to ``n`` free blocks (out of circulation until
        :meth:`grow`). Never parks below the admission promise —
        ``reserved`` blocks stay deliverable — so a live request can
        still never stall on the free list. Returns the count actually
        parked."""
        take = max(min(int(n), len(self._free) - self.reserved), 0)
        for _ in range(take):
            self._parked.append(self._free.pop())
        return take

    def grow(self, n):
        """Unpark up to ``n`` blocks back onto the free list. Returns
        the count actually returned to circulation."""
        give = max(min(int(n), len(self._parked)), 0)
        for _ in range(give):
            self._free.append(self._parked.pop())
        return give

    def extend(self, n):
        """``n`` physically NEW block ids (the batcher just grew the
        device pool's block axis): widen the refcount array and free
        the fresh ids. Returns the new ids."""
        n = int(n)
        if n <= 0:
            return []
        ids = list(range(self.num_blocks, self.num_blocks + n))
        self.num_blocks += n
        self.ref = np.concatenate(
            [self.ref, np.zeros((n,), np.int32)])
        # front of the pop-from-end free list: fresh high ids hand out
        # LAST, keeping low-id locality for the common case
        self._free = ids[::-1] + self._free
        return ids

    def alloc(self, n):
        """n fresh blocks at refcount 1 (raises when the free list is
        short — callers gate on available/reserved, so this firing
        means an accounting bug, not load)."""
        if n > len(self._free):
            raise RuntimeError(
                "paged KV free list exhausted (%d requested, %d free) "
                "— admission accounting should have prevented this"
                % (n, len(self._free)))
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            self.ref[b] = 1
        return ids

    def share(self, ids):
        """One more reference on each block (a new table mapping)."""
        for b in ids:
            if self.ref[b] < 1:
                raise RuntimeError("sharing unallocated block %d" % b)
            self.ref[b] += 1

    def release(self, ids):
        """Drop one reference per block; a block frees at zero."""
        for b in ids:
            self.ref[b] -= 1
            if self.ref[b] < 0:
                raise RuntimeError("double free of block %d" % b)
            if self.ref[b] == 0:
                self._free.append(b)

    def reserve(self, n):
        self.reserved += int(n)

    def unreserve(self, n):
        self.reserved -= int(n)
        assert self.reserved >= 0, "reservation accounting underflow"

    def check_invariants(self, mappings=None, quiesce=False):
        """Structural audit of the allocator — the standing leak/race
        detector every serving PR gets for free. Raises RuntimeError on
        the first violation, returns True otherwise.

        * conservation: every non-null block is EITHER on the free list
          (refcount 0) or referenced (refcount >= 1), never both, never
          neither — and the free list holds no duplicates.
        * ``mappings`` (optional): iterable of block-id lists (live lane
          tables + prefix-cache entries). Each block's refcount must
          equal the number of mappings that hold it, and no mapped
          block may sit on the free list.
        * ``reserved`` never exceeds the free list (``available >= 0``
          is the promise admission accounting makes).
        * pool conservation after every shrink/grow cycle:
          ``num_blocks - 1 == free + referenced + parked`` — parked
          blocks are disjoint from the free list, carry refcount 0,
          and hold no duplicates (the elastic ledger can neither leak
          nor double-count a block).
        * ``quiesce=True``: nothing live may remain — every block free
          or parked, every refcount zero, zero reservation (the
          zero-leak bar the overload harness asserts after a storm)."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise RuntimeError("free list holds duplicate block ids")
        parked = set(self._parked)
        if len(parked) != len(self._parked):
            raise RuntimeError("parked ledger holds duplicate block ids")
        if parked & free:
            raise RuntimeError(
                "blocks %s both parked and free" % sorted(parked & free))
        if 0 in free:
            raise RuntimeError("null block 0 leaked onto the free list")
        if 0 in parked:
            raise RuntimeError("null block 0 leaked onto the parked "
                               "ledger")
        if int(self.ref[0]) != 0:
            raise RuntimeError("null block 0 acquired a refcount")
        referenced = 0
        for b in range(1, self.num_blocks):
            r = int(self.ref[b])
            if b in parked:
                if r != 0:
                    raise RuntimeError(
                        "block %d is parked but refcount=%d" % (b, r))
                continue
            if b in free and r != 0:
                raise RuntimeError(
                    "block %d is free but refcount=%d" % (b, r))
            if b not in free and r < 1:
                raise RuntimeError(
                    "block %d leaked: refcount=%d and not free" % (b, r))
            if r >= 1:
                referenced += 1
        if len(free) + referenced + len(parked) != self.num_blocks - 1:
            raise RuntimeError(
                "pool conservation broken: %d free + %d referenced + "
                "%d parked != %d non-null blocks"
                % (len(free), referenced, len(parked),
                   self.num_blocks - 1))
        if self.reserved < 0:
            raise RuntimeError("negative reservation")
        if self.reserved > len(self._free):
            raise RuntimeError(
                "reserved %d exceeds free list %d — admission promised "
                "blocks that cannot be delivered"
                % (self.reserved, len(self._free)))
        if mappings is not None:
            want = {}
            for blocks in mappings:
                for b in blocks:
                    want[b] = want.get(b, 0) + 1
            for b, n in want.items():
                if b in free:
                    raise RuntimeError(
                        "mapped block %d sits on the free list" % b)
                if int(self.ref[b]) != n:
                    raise RuntimeError(
                        "block %d refcount=%d but %d mappings hold it"
                        % (b, int(self.ref[b]), n))
            for b in range(1, self.num_blocks):
                if int(self.ref[b]) > 0 and b not in want:
                    raise RuntimeError(
                        "block %d refcount=%d but no mapping holds it "
                        "(leak)" % (b, int(self.ref[b])))
        if quiesce:
            if self.reserved != 0:
                raise RuntimeError(
                    "quiesce with %d blocks still reserved"
                    % self.reserved)
            if len(self._free) + len(self._parked) \
                    != self.num_blocks - 1:
                raise RuntimeError(
                    "quiesce with %d of %d blocks leaked"
                    % (self.num_blocks - 1 - len(self._free)
                       - len(self._parked), self.num_blocks - 1))
        return True


class Request(object):
    __slots__ = ("rid", "tokens", "n_new", "emitted", "stop_token",
                 "seed", "priority", "key", "t_enq_ns", "t_admit_ns",
                 "t_first_ns", "t_last_ns", "slo_bad")

    def __init__(self, rid, prompt, n_new, stop_token=None, seed=0,
                 priority=0, key=None):
        self.rid = rid
        self.tokens = list(prompt)   # prompt + generated so far
        self.n_new = n_new
        self.emitted = 0             # generated count
        self.stop_token = stop_token
        self.seed = seed             # sampling seed (requeue needs it)
        self.priority = int(priority)  # larger = more important
        self.key = key               # idempotency key (dedup window)
        # request-lifecycle clock (perf_counter_ns; None with obs off):
        # enqueue -> admit -> first token -> last host-visible token
        self.t_enq_ns = None
        self.t_admit_ns = None
        self.t_first_ns = None
        self.t_last_ns = None
        self.slo_bad = False         # any observation missed its SLO

    @property
    def done(self):
        """Budget exhausted, or the stop token was emitted (the stop
        token itself is part of the stream, like an EOS the client
        sees)."""
        if self.emitted >= self.n_new:
            return True
        return (self.stop_token is not None and self.emitted > 0
                and self.tokens[-1] == self.stop_token)


class ContinuousBatcher(object):
    """Slot-based continuous batching over a shared ragged decode step.

    >>> srv = ContinuousBatcher(params, cfg, max_batch=8)
    >>> rid = srv.admit([1, 2, 3], n_new=16)      # None when full
    >>> finished = srv.step()                     # {rid: [tokens...]}

    Decoding is greedy by default; pool-level temperature/top_k/top_p
    sample instead (generate()'s rule), with a PER-REQUEST seed at
    admit(). Either way a request's output is identical to its solo
    tf.generate() run — greedy argmax, or the same per-row key chain
    (tested).

    `chunk_size=k` runs k decode steps per step() in one device
    dispatch (_jitted_ragged_chunk) — multi-step scheduling for
    high-dispatch-latency links. Token streams are unchanged (tested
    chunked == unchunked == solo); what changes is granularity:
    admission and eviction happen at chunk boundaries, and a lane
    whose request ends mid-chunk idles for the remainder.

    `cache_prefix(tokens)` prefills a shared prefix once (system
    prompt, few-shot preamble); admissions whose prompt starts with a
    cached prefix prefill only the suffix. LRU-bounded
    (prefix_cache_slots row caches on device).

    `pipeline_depth=d` (d >= 2) turns on CHUNK PIPELINING: up to d
    chunk dispatches ride in flight against the device-resident carry
    (cache, lane tokens/positions, sample keys), and each step() syncs
    only the OLDEST chunk's emissions — so the per-step host round
    trip amortizes over d chunks instead of gating every one.
    Admissions and evictions become tiny jitted lane patches applied
    to the carry between dispatches (bounded staleness: a request
    admitted while chunks are in flight enters at the NEXT dispatch
    boundary; chunks already in flight keep advancing its lane's
    previous occupant, whose emissions are discarded by request
    identity at sync). Token streams are bit-identical to
    pipeline_depth=1 and to solo generate() (tested). depth=1 is the
    synchronous batcher, unchanged.

    `paged=True` (default: MXNET_KV_PAGED) virtualizes the cache into
    fixed-size blocks (`block_size`, default MXNET_KV_BLOCK_SIZE=16):
    one per-layer pool of `num_blocks` blocks replaces the per-lane
    dense rows, each lane maps positions through an int32 block table,
    and capacity decouples from max_len — admission accounts in BLOCKS
    (the request's prompt + n_new worst-case demand must fit the free
    list) instead of assuming every lane owns a [max_len] row, so a
    pool sized for B dense lanes admits far more mixed-length
    requests. Blocks allocate lazily as positions advance (against an
    admission-time reservation, so a live lane never stalls) and free
    on finish/evict. `cache_prefix` becomes REFCOUNTED BLOCK SHARING:
    an admitted prompt starting with a cached prefix maps the prefix's
    full blocks into its table (stored once, copy-on-extend for the
    partial tail), and a shared block frees only at refcount zero.
    Streams stay bit-exact vs solo generate() — the gathered view
    feeds the identical attention contraction — and int8-KV, GQA,
    chunking, pipelining, and dispatch-failure requeue all compose.

    `spec_k=k` (default: MXNET_SPEC_K) turns every decode round into a
    SPECULATIVE draft/verify dispatch: k drafted tokens per lane —
    n-gram prompt-lookup over the lane's own stream by default
    (spec_ngram / MXNET_SPEC_NGRAM suffix length), or a small draft
    model when (draft_params, draft_cfg) are given — verified by one
    ragged [B, k+1] target pass with device-side acceptance, so each
    lane advances 1..k+1 tokens per target dispatch. Composes with
    chunking (chunk_size rounds per dispatch), pipelining (depth
    speculative dispatches in flight), and paging (tables advance by
    accepted counts; worst-case draft blocks are released at sync).
    spec_accept_floor > 0 (MXNET_SPEC_ACCEPT_FLOOR) enables the
    per-lane adaptive-k controller: a lane whose measured-acceptance
    EWMA drops below the floor drafts one token fewer next round
    (never below 1), and recovers one at a time while at/above it.
    Greedy-only; streams stay bit-exact vs solo generate() (tested
    across providers, paging, and depths). With spec_k unset nothing
    here runs — behavior AND dispatch count are unchanged (tested).

    `name` labels this replica's chaos site (serving.dispatch.<name>)
    so fleet tests can kill one replica of a router pool
    deterministically."""

    def __init__(self, params, cfg, max_batch=8, greedy=None,
                 temperature=1.0, top_k=None, top_p=None,
                 chunk_size=1, prefix_cache_slots=4, pipeline_depth=1,
                 paged=None, block_size=None, num_blocks=None,
                 name=None, spec_k=None, spec_ngram=None,
                 spec_accept_floor=None, draft_params=None,
                 draft_cfg=None, brownout=None, brownout_attain=None,
                 brownout_trip=None, brownout_clear=None,
                 journal=None):
        if cfg.max_len < 8:
            raise ValueError("max_len too small for the bucket floor")
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        self.params = params
        self.cfg = cfg
        self.max_batch = int(max_batch)
        # generate()'s rule, incl. greedy=False for pure ancestral
        # sampling (temperature=1.0 alone would read as greedy)
        sampling_requested = (temperature != 1.0 or top_k is not None
                              or top_p is not None)
        if greedy is None:
            greedy = not sampling_requested
        elif greedy and sampling_requested:
            raise ValueError(
                "greedy=True ignores temperature/top_k/top_p — pass "
                "greedy=False (or omit greedy) to sample")
        self.greedy = greedy
        self.chunk_size = int(chunk_size)
        self.pipeline_depth = int(pipeline_depth)
        self.name = name
        self._chaos_site = ("serving.dispatch" if name is None
                            else "serving.dispatch.%s" % name)
        self._controls = (self.greedy, float(temperature), top_k, top_p)
        # speculative dispatches (spec_k drafts verified per round)
        if spec_k is None:
            v = _fastenv.get("MXNET_SPEC_K")
            spec_k = int(v) if v else None
        self.spec_k = int(spec_k) if spec_k else None
        self._spec_on = self.spec_k is not None
        if self._spec_on:
            if self.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            if not self.greedy:
                raise ValueError(
                    "speculative dispatches are greedy-only: the "
                    "accept test compares drafts against the target "
                    "argmax (drop spec_k to sample)")
            if spec_ngram is None:
                v = _fastenv.get("MXNET_SPEC_NGRAM")
                spec_ngram = int(v) if v else 2
            self.spec_ngram = int(spec_ngram)
            if self.spec_ngram < 1:
                raise ValueError("spec_ngram must be >= 1")
            if spec_accept_floor is None:
                v = _fastenv.get("MXNET_SPEC_ACCEPT_FLOOR")
                spec_accept_floor = float(v) if v else 0.0
            self.spec_accept_floor = float(spec_accept_floor)
            if (draft_params is None) != (draft_cfg is None):
                raise ValueError(
                    "draft_params and draft_cfg come as a pair")
            if draft_cfg is not None:
                if draft_cfg.vocab_size != cfg.vocab_size:
                    raise ValueError(
                        "draft vocab %d != target vocab %d"
                        % (draft_cfg.vocab_size, cfg.vocab_size))
                if draft_cfg.max_len < cfg.max_len:
                    raise ValueError(
                        "draft max_len %d < target max_len %d — the "
                        "draft cache shares the target's lane "
                        "positions"
                        % (draft_cfg.max_len, cfg.max_len))
            self.draft_params = draft_params
            self.draft_cfg = draft_cfg
            self._spec_provider = ("model" if draft_params is not None
                                   else "ngram")
        elif draft_params is not None or draft_cfg is not None:
            raise ValueError("a draft model without spec_k does "
                             "nothing — set spec_k (or MXNET_SPEC_K)")
        else:
            self.spec_ngram = None
            self.spec_accept_floor = 0.0
            self.draft_params = self.draft_cfg = None
            self._spec_provider = None
        # target-model dispatches issued (sync steps, pipelined chunks,
        # speculative rounds' verify passes all count one per device
        # dispatch) — the denominator of dispatches-per-token, and the
        # off-path-silence invariant tests pin spec_k=None against
        self.dispatch_count = 0
        # speculative decode needs the device-resident carry even at
        # depth 1 (per-lane positions advance by data-dependent
        # accepted counts — mirroring them on the host would force a
        # sync per dispatch); pipelining needs it by construction
        self._device_carry = self.pipeline_depth > 1 or self._spec_on
        if paged is None:
            paged = (_fastenv.get("MXNET_KV_PAGED") or "") \
                not in ("", "0", "false", "False")
        self.paged = bool(paged)
        if self.paged:
            if block_size is None:
                block_size = int(_fastenv.get("MXNET_KV_BLOCK_SIZE",
                                              DEFAULT_KV_BLOCK_SIZE))
            self.block_size = int(block_size)
            if self.block_size < 1 \
                    or cfg.max_len % self.block_size:
                raise ValueError(
                    "block_size %d must divide max_len %d (set "
                    "MXNET_KV_BLOCK_SIZE accordingly)"
                    % (self.block_size, cfg.max_len))
            self._nb = cfg.max_len // self.block_size   # table width
            if num_blocks is None:
                # dense-equivalent HBM budget by default: every lane
                # could still hold a full-context row (+ the null block)
                num_blocks = self.max_batch * self._nb + 1
            self.num_blocks = int(num_blocks)
            self._alloc = BlockAllocator(self.num_blocks)
            if _membudget.enabled():
                # pool init is the one serving allocation whose size is
                # known analytically before any program compiles —
                # preflight it against live headroom like a jit boundary
                _membudget.preflight_bytes(
                    "serving.paged_pool",
                    tf.paged_cache_nbytes(cfg, self.num_blocks,
                                          self.block_size),
                    signature="%dx%d" % (self.num_blocks,
                                         self.block_size))
            self._pool = tf.init_paged_cache(cfg, self.num_blocks,
                                             self.block_size)
            self._tables = jnp.zeros((self.max_batch, self._nb),
                                     jnp.int32)
            self._lane_blocks = [[] for _ in range(self.max_batch)]
            self._lane_need = [0] * self.max_batch
            # scheduled position per lane = device pos after every
            # dispatched chunk (the pipelined carry never syncs it);
            # drives the lazy pre-dispatch block allocation
            self._sched_pos = np.zeros((self.max_batch,), np.int64)
            self._cache = None
        else:
            self._cache = tf.init_cache(cfg, self.max_batch)
        self._pos = np.zeros((self.max_batch,), np.int32)
        self._tok = np.zeros((self.max_batch,), np.int32)
        self._keys = np.zeros((self.max_batch, 2), np.uint32)
        self._slots = [None] * self.max_batch   # Request or None
        if self._device_carry:
            # device-resident lane carry (the host-side mirrors above
            # go unused): tok/pos/keys live on device between
            # dispatches, so a chunk dispatch uploads nothing and a
            # chunk sync downloads only the [k, B] emissions
            self._dev_tok = jnp.zeros((self.max_batch,), jnp.int32)
            self._dev_pos = jnp.zeros((self.max_batch,), jnp.int32)
            self._dev_keys = jnp.zeros((self.max_batch, 2), jnp.uint32)
            # in-flight dispatches, oldest first: (emissions [k, B],
            # per-lane rid snapshot at dispatch time) — speculative
            # records carry (targets, emits, rids, keff) instead
            self._inflight = deque()
            # resolved once — a pipelined dispatch must not pay the
            # _serving_jit registry lookup per chunk
            if self._spec_on:
                self._spec_fn = _jitted_spec_chunk(
                    cfg, self.draft_cfg, self.spec_k,
                    self.spec_ngram, self.chunk_size, self.paged,
                    self._spec_provider == "model")
            else:
                self._pipe_fn = (
                    _jitted_pipeline_chunk_paged(cfg, *self._controls,
                                                 self.chunk_size)
                    if self.paged else
                    _jitted_pipeline_chunk(cfg, *self._controls,
                                           self.chunk_size))
            self._patch_fn = _jitted_lane_patch(cfg)
        if self._spec_on:
            # per-lane adaptive k: effective draft length (masked
            # inside the static-width program) and the measured
            # acceptance EWMA the floor controller reads
            self._keff = np.full((self.max_batch,), self.spec_k,
                                 np.int32)
            self._accept_ewma = np.ones((self.max_batch,), np.float64)
            self._spec_rounds = 0
            self._spec_drafted = 0
            self._spec_accepted = 0
            if self._spec_provider == "ngram":
                self._dev_hist = jnp.zeros(
                    (self.max_batch, cfg.max_len), jnp.int32)
                self._hist_fn = _jitted_hist_row(cfg)
            elif self.paged:
                # the draft pool SHARES the target's block tables: one
                # table row covers both models' positions, so block
                # accounting stays single-ledger (the cost: prefix
                # sharing is disabled — cached blocks hold target K/V
                # only; see admit()/cache_prefix)
                self._dpool = tf.init_paged_cache(
                    self.draft_cfg, self.num_blocks, self.block_size)
            else:
                self._dcache = tf.init_cache(self.draft_cfg,
                                             self.max_batch)
        # dispatch-failure recovery: a failed decode dispatch frees the
        # lanes and requeues the live requests (greedy streams resume
        # bit-exactly) instead of wedging the batcher; consecutive
        # failures past the cap re-raise — a deterministic fault must
        # not become a silent requeue loop
        self._dispatch_failures = 0
        self._max_dispatch_failures = 3
        self._next_rid = 0
        # goodput accounting: completed (delivered) tokens since the
        # first admission — feeds the serving.goodput_tok_s gauge
        self._completed_tokens = 0
        self._t_serve_start_ns = None
        # weight-version identity (integrity.tree_fingerprint over the
        # served params) — lazily computed once, cached: replicas of
        # one fleet must agree, and the router checks they do
        self._weight_fp = None
        if _obs.enabled():
            _obs_http.maybe_start()    # MXNET_OBS_HTTP live scrape
        # prefix cache, LRU-bounded (prefix_cache_slots). Dense mode:
        # tuple(tokens) -> (row_cache, last_row_logits) — one [1,
        # max_len] row cache on device per entry. Paged mode:
        # tuple(tokens) -> (block_ids, last_row_logits) — the prefix
        # lives IN the pool, refcounted, and admissions map its full
        # blocks instead of copying them
        self._prefix_cache = {}
        self._prefix_slots = int(prefix_cache_slots)
        # KV-pressure preemption: admit(priority=...) may evict a
        # strictly lower-priority lane to cover a block shortfall; the
        # victim lands here as (Request, preempt_ns) for the caller
        # (router._admit_queued, or run()) to resume bit-exactly via
        # admit_continuation()
        self.preempted = []
        # brownout ladder (MXNET_SERVING_BROWNOUT=1): rung 0 is
        # healthy; sustained SLO-attainment drop, block exhaustion, or
        # (membudget-armed) device-headroom starvation climbs one rung
        # at a time — 1: clamp the speculative draft width, 2: stop
        # admitting new shareable prefixes, 3: throttle admission to
        # one per scheduling round, 4: kv_shrink — park part of the KV
        # pool (returned on the walk back down), 5: shed the lowest
        # priority class — and sustained recovery walks back down
        # (hysteresis: the trip and clear streaks differ)
        if brownout is None:
            brownout = (_fastenv.get("MXNET_SERVING_BROWNOUT") or "") \
                not in ("", "0", "false", "False")
        self.brownout = bool(brownout)
        if brownout_attain is None:
            v = _fastenv.get("MXNET_SERVING_BROWNOUT_ATTAIN")
            brownout_attain = float(v) if v else 0.9
        self._brownout_attain = float(brownout_attain)
        if brownout_trip is None:
            v = _fastenv.get("MXNET_SERVING_BROWNOUT_TRIP")
            brownout_trip = int(v) if v else 3
        self._brownout_trip = int(brownout_trip)
        if brownout_clear is None:
            v = _fastenv.get("MXNET_SERVING_BROWNOUT_CLEAR")
            brownout_clear = int(v) if v else 8
        self._brownout_clear = int(brownout_clear)
        self._bo_rung = 0
        self._bo_bad = 0
        self._bo_good = 0
        self._round_admits = 0
        # blocks the kv_shrink rung parked (returned when it clears)
        self._bo_parked = 0
        # MXNET_SERVING_DEBUG=1: allocator invariants audited at every
        # idle point (cheap standing leak detector; tests call
        # check_invariants unconditionally)
        self._debug = (_fastenv.get("MXNET_SERVING_DEBUG") or "") \
            not in ("", "0", "false", "False")
        # request write-ahead journal (models/journal.py): every
        # admission / synced emission / preemption / finish appends a
        # CRC-guarded record, and recover() replays it after a crash.
        # journal=None reads MXNET_SERVING_JOURNAL_DIR (a NAMED replica
        # journals into a per-replica subdirectory, so an in-process
        # fleet's segments never collide); journal=False is off even
        # with the env set (the router journals for its fleet instead);
        # a str is a directory; a RequestJournal is used as-is. Off is
        # one guarded branch per hook — dispatch count and numerics are
        # bit-identical with the journal unset (tested).
        if journal is None:
            jd = _fastenv.get("MXNET_SERVING_JOURNAL_DIR")
            if jd and name is not None:
                jd = os.path.join(jd, name)
            journal = _journal.RequestJournal(jd) if jd else False
        elif isinstance(journal, str):
            journal = _journal.RequestJournal(journal)
        self._journal = journal or None
        # idempotency dedup window: key -> live rid, and key ->
        # (rid, final tokens) once finished; a duplicate submit returns
        # the ORIGINAL rid (serving.dedup_hits counts them) and a
        # finished duplicate re-delivers through _pending_finished
        self._idem = {}
        self._idem_done = {}
        # results to deliver at the next step() without a dispatch:
        # dedup re-deliveries and streams drained by swap_weights()
        self._pending_finished = {}
        if _obs.enabled():
            # flight recorder: incident bundles carry this replica's
            # health snapshot (weak-ref'd — the recorder never pins a
            # dead batcher); the time-series sampler daemon starts once
            # per process, shared by every replica
            _flight.register_context(
                "serving.%s" % (self.name or "batcher"),
                self.health_snapshot)
            _timeseries.maybe_start()

    # ---- admission ----

    @property
    def active_count(self):
        return sum(1 for s in self._slots if s is not None)

    @property
    def has_capacity(self):
        """A free lane — and, under paging, at least one block of
        unpromised capacity (free minus reservations, counting
        evictable prefix entries): admission accounts in BLOCKS, so a
        pool can be full long before its lanes are (and vice versa).
        The per-request check is admit() itself — a specific prompt's
        worst-case demand can still exceed one free block."""
        if self.active_count >= self.max_batch:
            return False
        if self.paged:
            return self._alloc.available >= 1 \
                or bool(self._prefix_cache)
        return True

    @property
    def free_blocks(self):
        """Unallocated pool blocks (None when not paged) — the router's
        primary load signal."""
        return self._alloc.free_blocks if self.paged else None

    @property
    def weight_fingerprint(self):
        """8-hex id of the served weights (one
        ``integrity.tree_fingerprint`` call, cached — the params are
        immutable for the batcher's lifetime). The same id appears in
        checkpoint manifests (``param_fingerprint``), so an operator
        can trace exactly which checkpoint a replica serves; the
        router compares it across the fleet. Also published as the
        ``serving.weight_version`` gauge (the id as an integer —
        < 2^32, exact in a float64) for /healthz scrapers."""
        if self._weight_fp is None:
            self._weight_fp = _integrity.params_fingerprint(self.params)
            if _obs.enabled():
                _obs.gauge("serving.weight_version").set(
                    int(self._weight_fp, 16))
        return self._weight_fp

    def health_snapshot(self):
        """The per-replica routing signals, /healthz-shaped (same names
        a scraper reads off MXNET_OBS_HTTP's /healthz `counters`):
        lane occupancy, paged-pool headroom, rolling SLO attainment,
        the weight-version fingerprint.
        models/router.py polls this for in-process replicas; a
        multi-process fleet scrapes the HTTP endpoint instead."""
        active = self.active_count
        snap = {
            "serving.lane_occupancy": active,
            "serving.lane_utilization": active / float(self.max_batch),
            "serving.slo_attainment": _slo.attainment(),
            "serving.weight_fingerprint": self.weight_fingerprint,
            "serving.weight_version": int(self.weight_fingerprint, 16),
        }
        if self._journal is not None:
            snap["serving.journal_depth_bytes"] = \
                self._journal.depth_bytes
            snap["serving.journal_lag_records"] = \
                self._journal.lag_records
        if self.paged:
            usable = self.num_blocks - 1
            snap["serving.kv_free_blocks"] = self._alloc.free_blocks
            snap["serving.kv_available_blocks"] = self._alloc.available
            snap["serving.kv_block_utilization"] = \
                (usable - self._alloc.free_blocks) / float(usable)
            if self._alloc.parked_blocks:
                snap["serving.kv_parked_blocks"] = \
                    self._alloc.parked_blocks
        if _membudget.armed():
            # live device headroom (None on platforms without memory
            # stats): the router's starvation gate stops admitting to
            # a replica whose headroom fell below the reserve
            hb = _membudget.headroom_bytes()
            if hb is not None:
                snap["mem.headroom_bytes"] = hb
        if self._spec_on:
            snap["serving.spec_draft_ratio"] = (
                self._spec_accepted / self._spec_drafted
                if self._spec_drafted else 1.0)
            snap["serving.spec_k_live"] = float(np.mean(self._keff))
        if self.brownout:
            snap["serving.brownout_rung"] = self._bo_rung
        return snap

    def check_invariants(self, quiesce=False):
        """Audit paged block accounting against every live mapping —
        lane tables plus prefix-cache entries (see
        BlockAllocator.check_invariants). ``quiesce=True`` additionally
        demands zero live lanes, an empty prefix cache's worth of
        references released, and a whole free list — the zero-leak bar.
        A no-op (True) when not paged."""
        if not self.paged:
            return True
        mappings = [b for b in self._lane_blocks if b]
        mappings += [blocks for blocks, _ in
                     self._prefix_cache.values()]
        self._alloc.check_invariants(
            mappings=mappings,
            quiesce=quiesce and not self._prefix_cache)
        if quiesce and self.active_count:
            raise RuntimeError(
                "quiesce with %d live requests" % self.active_count)
        for i, req in enumerate(self._slots):
            if req is None and self._lane_blocks[i]:
                raise RuntimeError(
                    "freed lane %d still maps %d blocks"
                    % (i, len(self._lane_blocks[i])))
            if req is None and self._lane_need[i]:
                raise RuntimeError(
                    "freed lane %d still reserves toward a %d-block "
                    "lifetime" % (i, self._lane_need[i]))
        return True

    def _debug_idle_check(self):
        """The MXNET_SERVING_DEBUG=1 idle-point audit: whenever the
        pool drains, the allocator must balance (every future serving
        change inherits this leak detector for free)."""
        if self._debug and self.paged and self.active_count == 0:
            self.check_invariants()

    # ---- paged block accounting ----

    def _block_math(self, t_p, total_len):
        """(lifetime_blocks, init_blocks) for a request whose final
        stream is `total_len` tokens from a `t_p`-token prompt: the
        deepest cache write of its life is position total_len - 2 (the
        final emitted token is never written), and admission must also
        cover position t_p — the first decode write target."""
        last_pos = max(t_p, total_len - 2)
        return (last_pos // self.block_size + 1,
                t_p // self.block_size + 1)

    def _evict_prefixes(self, demand, keep=None):
        """LRU-evict cached prefixes until `demand` blocks are
        available (or nothing evictable remains). Released blocks hit
        the free list only at refcount zero, so an entry shared with
        live lanes yields nothing until they finish — which is exactly
        the safety the refcount exists for. `keep` shields the entry
        the in-progress admission is about to share."""
        while self._alloc.available < demand:
            victim = next(
                (k for k in self._prefix_cache if k != keep
                 and any(self._alloc.ref[b] == 1
                         for b in self._prefix_cache[k][0])),
                None)                  # oldest evictable first (LRU);
            if victim is None:         # an entry pinned by live lanes
                return False           # would free nothing — skip it
            blocks, _ = self._prefix_cache.pop(victim)
            self._alloc.release(blocks)
            if _obs.enabled():
                _obs.record_instant(
                    "serving.prefix_evict", cat="serving",
                    args={"prefix_len": len(victim),
                          "blocks": len(blocks)})
        return True

    def _lookup_prefix_blocks(self, prompt):
        """Paged twin of _lookup_prefix: longest cached prefix ->
        (p_len, block_ids, last_row_logits), LRU-refreshed; (0, [],
        None) on a miss. The blocks stay refcounted by the entry —
        admission adds its own reference per shared FULL block."""
        best = None
        for key in self._prefix_cache:
            if len(key) <= len(prompt) \
                    and tuple(prompt[:len(key)]) == key:
                if best is None or len(key) > len(best):
                    best = key
        if best is None:
            return 0, [], None
        hit = self._prefix_cache.pop(best)
        self._prefix_cache[best] = hit               # LRU refresh
        return len(best), hit[0], hit[1]

    def cache_prefix(self, tokens):
        """Prefill `tokens` once and keep the row cache + last-row
        logits for reuse: a later admit() whose prompt starts with
        these tokens prefills only the suffix (system prompts,
        few-shot preambles — the shared-prefix serving pattern).
        The prefix is processed at its exact length (no bucket pad),
        so the cached row holds zeros beyond it and nothing stale is
        ever attendable. Entries are LRU-bounded by
        prefix_cache_slots; each holds one full-width row cache on
        device. Returns the prefix length."""
        if self._prefix_slots < 1:
            raise ValueError("prefix caching disabled "
                             "(prefix_cache_slots=0)")
        if self.paged and self._spec_on \
                and self._spec_provider == "model":
            raise ValueError(
                "prefix sharing is unavailable with a paged draft "
                "model: cached blocks hold target K/V only, and the "
                "draft pool rides the same block tables (use the "
                "n-gram provider, or dense caching)")
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        if not toks:
            raise ValueError("empty prefix")
        if len(toks) >= self.cfg.max_len:
            raise ValueError("prefix %d must leave room under "
                             "max_len %d" % (len(toks),
                                             self.cfg.max_len))
        if self.paged:
            return self._cache_prefix_paged(toks)
        key = tuple(toks)
        hit = self._prefix_cache.pop(key, None)
        if hit is None:
            logits, row_cache = tf._jitted_prefill_chunk_row(self.cfg)(
                self.params, tf.init_cache(self.cfg, 1),
                jnp.asarray([toks], jnp.int32),
                jnp.int32(0), jnp.int32(len(toks) - 1))
            hit = (row_cache, logits)
        self._prefix_cache[key] = hit                # insert/refresh
        while len(self._prefix_cache) > self._prefix_slots:
            self._prefix_cache.pop(next(iter(self._prefix_cache)))
        return len(toks)

    def _cache_prefix_paged(self, toks):
        """Paged cache_prefix: the prefix is prefilled once into POOL
        blocks (refcount 1 held by the cache entry) and shared by
        admissions at block granularity. A nested shorter prefix's
        full blocks are themselves shared into the new entry — nesting
        costs only the tail. LRU-bounded like the dense path, except
        the bound (and block pressure from admissions) releases
        references, not device rows."""
        key = tuple(toks)
        hit = self._prefix_cache.pop(key, None)
        if hit is not None:
            self._prefix_cache[key] = hit            # LRU refresh
            return len(toks)
        p = len(toks)
        bs = self.block_size
        # share a nested cached prefix's full blocks, if any
        p_sub, sub_blocks, _ = self._lookup_prefix_blocks(toks)
        s = p_sub // bs
        nb = (p + bs - 1) // bs
        own_n = nb - s
        if own_n > self._alloc.available \
                and not self._evict_prefixes(
                    own_n, keep=tuple(toks[:p_sub]) if p_sub else None):
            raise RuntimeError(
                "no free KV blocks for a %d-token prefix (%d needed, "
                "%d available)" % (p, own_n, self._alloc.available))
        if p_sub:
            nb_sub = (p_sub + bs - 1) // bs
            row = _jitted_gather_row(self.cfg, nb_sub)(
                self._pool, jnp.asarray(sub_blocks[:nb_sub], jnp.int32))
        else:
            row = tf.init_cache(self.cfg, 1)
        # exact-length suffix prefill (no bucket pad): the cached
        # blocks hold zeros beyond the prefix, so nothing stale is
        # ever attendable through a sharer's table
        logits, row = tf._jitted_prefill_chunk_row(self.cfg)(
            self.params, row,
            jnp.asarray([toks[p_sub:]], jnp.int32),
            jnp.int32(p_sub), jnp.int32(p - p_sub - 1))
        own = self._alloc.alloc(own_n)
        if s:
            self._alloc.share(sub_blocks[:s])
        self._pool = _jitted_block_write(self.cfg, own_n)(
            self._pool, row, jnp.asarray(own, jnp.int32),
            jnp.int32(s * bs))
        self._prefix_cache[key] = (sub_blocks[:s] + own, logits)
        while len(self._prefix_cache) > self._prefix_slots:
            old = next(iter(self._prefix_cache))
            blocks, _ = self._prefix_cache.pop(old)
            self._alloc.release(blocks)
        if _obs.enabled():
            self._publish_occupancy()
        return p

    def _lookup_prefix(self, prompt):
        """Longest cached prefix of `prompt` -> (p_len, row_cache,
        last_row_logits-or-None). The cached trees are never mutated
        (prefill returns new arrays; the chunk-row wrapper does not
        donate), so one prefix serves any number of admissions."""
        best = None
        for key in self._prefix_cache:
            if len(key) <= len(prompt) \
                    and tuple(prompt[:len(key)]) == key:
                if best is None or len(key) > len(best):
                    best = key
        if best is None:
            return 0, tf.init_cache(self.cfg, 1), None
        hit = self._prefix_cache.pop(best)
        self._prefix_cache[best] = hit               # LRU refresh
        return len(best), hit[0], hit[1]

    def _paged_prefill(self, prompt, t_p, p_len, pfx_blocks,
                       pfx_logits):
        """Build the admission row cache through the pool: gather the
        cached prefix's blocks into a [1, max_len] row (zero-padded),
        prefill the suffix at bucket width (exactly the dense path's
        compile-once-per-bucket rule), and return (last_logits,
        row_cache). The shared blocks themselves are untouched — the
        row exists so the suffix's attention can read the prefix."""
        bs = self.block_size
        if p_len:
            nb_pfx = (p_len + bs - 1) // bs
            row_cache = _jitted_gather_row(self.cfg, nb_pfx)(
                self._pool,
                jnp.asarray(pfx_blocks[:nb_pfx], jnp.int32))
        else:
            row_cache = tf.init_cache(self.cfg, 1)
        if p_len == t_p:
            return pfx_logits[0], row_cache
        width = min(_bucket(t_p - p_len), self.cfg.max_len - p_len)
        padded = np.zeros((1, width), np.int32)
        padded[0, : t_p - p_len] = prompt[p_len:]
        logits, row_cache = tf._jitted_prefill_chunk_row(self.cfg)(
            self.params, row_cache, jnp.asarray(padded),
            jnp.int32(p_len), jnp.int32(t_p - p_len - 1))
        return logits[0], row_cache

    def _paged_map_lane(self, slot, t_p, row_cache, p_len, pfx_blocks,
                        lifetime, init_n):
        """Map a lane's block table for a fresh admission: the cached
        prefix's FULL blocks are shared in place (refcount++), the
        remainder through position t_p is freshly allocated and
        written whole-block from the row cache (copy-on-extend: a
        partial prefix tail is copied, never written shared), the rest
        of the lifetime is reserved for the lazy per-dispatch
        extension, and unneeded entries stay on the null block."""
        bs = self.block_size
        shared = p_len // bs
        own_n = init_n - shared        # >= 1: covers the first write
        own = self._alloc.alloc(own_n)
        if shared:
            self._alloc.share(pfx_blocks[:shared])
        self._alloc.reserve(lifetime - init_n)
        self._pool = _jitted_block_write(self.cfg, own_n)(
            self._pool, row_cache, jnp.asarray(own, jnp.int32),
            jnp.int32(shared * bs))
        lane = list(pfx_blocks[:shared]) + own
        trow = np.zeros((self._nb,), np.int32)
        trow[: len(lane)] = lane
        self._tables = _jitted_table_row(self.cfg)(
            self._tables, jnp.int32(slot), jnp.asarray(trow))
        self._lane_blocks[slot] = lane
        self._lane_need[slot] = lifetime
        self._sched_pos[slot] = t_p

    def _ensure_coverage(self, k):
        """Allocate (lazily) the blocks the next k decode positions of
        every live lane will write, drawn from the reservation admit()
        made — the free list cannot run dry here, by accounting.
        Entries past a lane's lifetime need stay null: a request that
        finishes mid-chunk coasts its remaining writes into the
        garbage sink."""
        bs = self.block_size
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            pos = int(self._sched_pos[i] if self._device_carry
                      else self._pos[i])
            end = min((pos + k - 1) // bs, self._lane_need[i] - 1,
                      self._nb - 1)
            while len(self._lane_blocks[i]) <= end:
                bid = self._alloc.alloc(1)[0]
                self._alloc.unreserve(1)
                j = len(self._lane_blocks[i])
                self._lane_blocks[i].append(bid)
                self._tables = _jitted_table_entry(self.cfg)(
                    self._tables, jnp.int32(i), jnp.int32(j),
                    jnp.int32(bid))

    def admit(self, prompt, n_new, seed=0, stop_token=None,
              enqueued_ns=None, priority=0, key=None):
        """Prefill `prompt` into a free slot; returns the request id,
        or None when every slot is busy. The first generated token is
        produced here (from the prefill logits), so a request with
        n_new=1 never occupies a decode lane. `seed` drives this
        request's sampling chain (ignored under greedy), exactly as
        generate(seed=...) would. `stop_token` ends the request early
        when emitted (EOS semantics; the stop token is included in the
        returned stream). `enqueued_ns` (perf_counter_ns) is when the
        request entered the caller's queue — with telemetry on it
        anchors the serving.queue_wait span and the serving.queue_ms /
        serving.ttft_ms histograms (run()/stream() pass it; without it
        TTFT is measured from this call). `priority` (larger = more
        important, default 0) drives KV-pressure PREEMPTION under
        paging: when the block pool cannot cover this admission, the
        lowest-priority strictly-below-`priority` lane is evicted to
        ``self.preempted`` (its synced prefix captured for a bit-exact
        resume via admit_continuation()) and its blocks fund this
        admission. With uniform priorities nothing is ever preempted.
        `key` is an optional IDEMPOTENCY key: a duplicate submission
        (same key, this batcher's dedup window) returns the ORIGINAL
        request's rid instead of double-admitting — still live, the
        caller keeps consuming its stream; already finished, the
        recorded result is re-delivered by the next step(). Dedup hits
        count ``serving.dedup_hits``; with a journal attached the
        window survives restarts (recover() repopulates it)."""
        if n_new < 1:
            raise ValueError("n_new must be >= 1")
        if key is not None:
            hit = self._idem.get(key)
            if hit is None and key in self._idem_done:
                rid0, toks0 = self._idem_done[key]
                self._pending_finished[rid0] = list(toks0)
                hit = rid0
            if hit is not None:
                _obs.counter("serving.dedup_hits").add(1)
                if _obs.enabled():
                    _obs.record_instant(
                        "serving.dedup", cat="serving",
                        args={"rid": hit, "key": str(key)})
                return hit
        # the sampling path below rebinds `key` to the PRNG chain —
        # keep the idempotency key under its own name past that point
        idem_key = key
        obs_on = _obs.enabled()
        t0_ns = time.perf_counter_ns() if obs_on else None
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        t_p = len(prompt)
        if t_p < 1:
            raise ValueError("empty prompt")
        if t_p + n_new > self.cfg.max_len:
            raise ValueError("prompt+n_new %d exceeds max_len %d"
                             % (t_p + n_new, self.cfg.max_len))
        if self.brownout and self._bo_rung > 0 \
                and not self._brownout_admit_ok(priority):
            return None
        slot = next((i for i, s in enumerate(self._slots) if s is None),
                    None)
        if slot is None:
            return None
        if self.paged:
            # block-accounted admission: the prompt + n_new worst-case
            # demand (minus the cached prefix's shareable full blocks)
            # must fit the unpromised free list — LRU prefix eviction
            # may make room, a live lane's blocks never move
            if self._spec_on and self._spec_provider == "model":
                # the draft pool rides the TARGET's block tables, and
                # cached prefix blocks hold target K/V only — sharing
                # one would leave the draft cache blind over the
                # prefix, so model-draft paged serving prefills whole
                # (cache_prefix refuses; see there)
                p_len, pfx_blocks, pfx_logits = 0, [], None
            elif self.brownout and self._bo_rung >= 2:
                # brownout rung 2+: no NEW shared-prefix admissions —
                # sharing pins blocks past the sharer's own lifetime,
                # the opposite of what an exhausted pool needs
                p_len, pfx_blocks, pfx_logits = 0, [], None
            else:
                p_len, pfx_blocks, pfx_logits = \
                    self._lookup_prefix_blocks(prompt)
            shared = p_len // self.block_size
            lifetime, init_n = self._block_math(t_p, t_p + n_new)
            demand = lifetime - shared
            if demand > self.num_blocks - 1:
                raise ValueError(
                    "request needs %d KV blocks but the pool has only "
                    "%d usable (num_blocks=%d incl. the null block)"
                    % (demand, self.num_blocks - 1, self.num_blocks))
            if demand > self._alloc.available and not \
                    self._evict_prefixes(
                        demand,
                        keep=tuple(prompt[:p_len]) if p_len else None) \
                    and not self._preempt_for(demand, priority):
                return None
        rid = self._next_rid
        pre_span = _obs.span("serving.prefill", cat="serving", rid=rid,
                             lane=slot, prompt_tokens=t_p).start()
        if self.paged:
            last, row_cache = self._paged_prefill(
                prompt, t_p, p_len, pfx_blocks, pfx_logits)
            self._paged_map_lane(slot, t_p, row_cache, p_len,
                                 pfx_blocks, lifetime, init_n)
        else:
            # longest cached prefix (0 + a fresh row cache when none):
            # only the suffix prefills
            p_len, row_cache, pfx_logits = self._lookup_prefix(prompt)
            if p_len == t_p:
                last = pfx_logits[0]   # whole prompt is the prefix
            else:
                # clamp: the bucket can pass max_len (e.g. max_len=96,
                # suffix 70 -> bucket 128) and the cache axis is
                # max_len wide; width >= suffix always holds since
                # t_p + n_new <= max_len
                width = min(_bucket(t_p - p_len),
                            self.cfg.max_len - p_len)
                padded = np.zeros((1, width), np.int32)
                padded[0, : t_p - p_len] = prompt[p_len:]
                # one compiled prefill per bucket width (prefill_chunk
                # already specializes per chunk shape); fills positions
                # [p_len, p_len+width) — rows beyond t_p are pad
                # garbage that decode overwrites before attention can
                # reach them
                logits, row_cache = \
                    tf._jitted_prefill_chunk_row(self.cfg)(
                        self.params, row_cache, jnp.asarray(padded),
                        jnp.int32(p_len), jnp.int32(t_p - p_len - 1))
                last = logits[0]
        if self._device_carry:
            # prefill-into-lane, all device-side: pick the first token
            # on device (generate()'s exact chain), patch the row
            # cache and the lane's (tok, pos, key) into the carry —
            # the patches consume the LAST dispatch's output buffers,
            # so they take effect at the next dispatch boundary while
            # the chunks already in flight keep reading their own
            # (older) buffers. The one host pull here is the first
            # token SCALAR, not the [vocab] logits row.
            first_dev, key = _jitted_admit_token(
                self.cfg, *self._controls)(last, jnp.int32(seed))
            with _obs.span("serving.patch", cat="serving", kind="admit",
                           lane=slot):
                if not self.paged:   # paged: blocks already scattered
                    self._cache = _jitted_slot_write(self.cfg)(
                        self._cache, row_cache, jnp.int32(slot))
                self._dev_tok, self._dev_pos, self._dev_keys = \
                    self._patch_fn(self._dev_tok, self._dev_pos,
                                   self._dev_keys, jnp.int32(slot),
                                   first_dev, jnp.int32(t_p), key)
            first = int(first_dev)
        else:
            if self.greedy:
                first = int(np.argmax(np.asarray(last)))
            else:
                # mirror generate()'s chain: key=PRNGKey(seed); split
                # once for the prefill token, carry the key into the
                # step loop
                key = jax.random.PRNGKey(seed)
                key, sub = jax.random.split(key)
                _, temperature, top_k, top_p = self._controls
                first = int(tf._sample_logits(last[None], sub,
                                              temperature, top_k,
                                              top_p)[0])
                self._keys[slot] = np.asarray(key, np.uint32)
            if not self.paged:         # paged: blocks already scattered
                self._cache = _jitted_slot_write(self.cfg)(
                    self._cache, row_cache, jnp.int32(slot))
            self._pos[slot] = t_p      # next decode writes position t_p
            self._tok[slot] = first
        if self._spec_on:
            self._spec_admit(slot, prompt, t_p, first)
        pre_span.stop()
        req = Request(rid, prompt, n_new, stop_token, seed=seed,
                      priority=priority, key=idem_key)
        self._next_rid += 1
        req.tokens.append(first)
        req.emitted = 1
        self._slots[slot] = req
        self._round_admits += 1
        if idem_key is not None:
            self._idem[idem_key] = req.rid
        if self._journal is not None:
            # the submit record carries the first token (emitted=1):
            # replay resumes as a continuation from exactly here
            self._journal.append_submit(
                req.rid, req.tokens, n_new, seed=seed,
                stop_token=stop_token, priority=priority,
                key=idem_key, emitted=1)
        if obs_on:
            self._note_admit(req, slot, t0_ns, enqueued_ns)
        return req.rid

    def admit_continuation(self, tokens, n_more, seed=0, emitted=1,
                           stop_token=None, priority=0,
                           preempted_ns=None, resumes=None, key=None):
        """Resume a suspended stream BIT-exactly: `tokens` is the full
        synced history (prompt + `emitted` generated tokens), `n_more`
        the remaining budget. The cache is re-prefilled over
        tokens[:-1] and decode resumes feeding the last token at its
        true position — the requeue identity — and, under sampling,
        the per-request key chain is REPLAYED to its post-`emitted`
        state (split applied `emitted` times from PRNGKey(seed)), so a
        preempted-then-resumed stream is bit-identical to its
        uninterrupted solo run, sampled included (the dispatch-failure
        requeue path keeps its coarser reseed contract). Returns the
        NEW request id, or None when no lane/blocks are free.
        `preempted_ns` (perf_counter_ns of the preemption) feeds the
        serving.preempt_stall_ms histogram. `resumes` names the
        journaled rid this continuation supersedes (the park record's
        owner): with a journal attached the old rid is tombstoned
        (reason ``resume``) so a later replay resumes the NEW record
        only. `key` carries the original idempotency key forward."""
        if n_more < 1:
            raise ValueError("n_more must be >= 1")
        if emitted < 1:
            raise ValueError(
                "a continuation resumes a stream that emitted at "
                "least its first token (emitted >= 1)")
        obs_on = _obs.enabled()
        tokens = [int(t) for t in np.asarray(tokens).reshape(-1)]
        m = len(tokens) - 1
        if m < 1:
            raise ValueError("continuation needs prompt + first token")
        if len(tokens) + n_more > self.cfg.max_len:
            raise ValueError("history+n_more %d exceeds max_len %d"
                             % (len(tokens) + n_more, self.cfg.max_len))
        slot = next((i for i, s in enumerate(self._slots) if s is None),
                    None)
        if slot is None:
            return None
        if self.paged:
            lifetime, init_n = self._block_math(m, len(tokens) + n_more)
            if lifetime > self.num_blocks - 1:
                raise ValueError(
                    "continuation needs %d KV blocks but the pool has "
                    "only %d usable" % (lifetime, self.num_blocks - 1))
            if lifetime > self._alloc.available and not \
                    self._evict_prefixes(lifetime) \
                    and not self._preempt_for(lifetime, priority):
                return None
        rid = self._next_rid
        pre_span = _obs.span("serving.prefill", cat="serving", rid=rid,
                             lane=slot, kind="resume",
                             prompt_tokens=m).start()
        ctx, last = tokens[:-1], tokens[-1]
        row_cache = tf.init_cache(self.cfg, 1)
        width = min(_bucket(m), self.cfg.max_len)
        padded = np.zeros((1, width), np.int32)
        padded[0, :m] = ctx
        _, row_cache = tf._jitted_prefill_chunk_row(self.cfg)(
            self.params, row_cache, jnp.asarray(padded),
            jnp.int32(0), jnp.int32(m - 1))
        key_np = self._resume_key(seed, emitted)
        if self.paged:
            self._paged_map_lane(slot, m, row_cache, 0, [], lifetime,
                                 init_n)
        else:
            self._cache = _jitted_slot_write(self.cfg)(
                self._cache, row_cache, jnp.int32(slot))
        if self._device_carry:
            with _obs.span("serving.patch", cat="serving",
                           kind="resume", lane=slot):
                self._dev_tok, self._dev_pos, self._dev_keys = \
                    self._patch_fn(self._dev_tok, self._dev_pos,
                                   self._dev_keys, jnp.int32(slot),
                                   jnp.int32(last), jnp.int32(m),
                                   jnp.asarray(key_np))
        else:
            self._pos[slot] = m
            self._tok[slot] = last
            self._keys[slot] = key_np
        if self._spec_on:
            self._spec_admit(slot, ctx, m, last)
        pre_span.stop()
        req = Request(rid, tokens, emitted + n_more, stop_token,
                      seed=seed, priority=priority, key=key)
        req.emitted = emitted
        self._next_rid += 1
        self._slots[slot] = req
        self._round_admits += 1
        if key is not None:
            self._idem[key] = req.rid
        if self._journal is not None:
            if resumes is not None:
                self._journal.append_finish(resumes, "resume")
            self._journal.append_submit(
                req.rid, req.tokens, req.n_new, seed=seed,
                stop_token=stop_token, priority=priority, key=key,
                emitted=emitted)
        if obs_on:
            t1 = time.perf_counter_ns()
            req.t_admit_ns = req.t_first_ns = req.t_last_ns = t1
            if preempted_ns is not None:
                _obs.histogram("serving.preempt_stall_ms", "ms") \
                    .observe((t1 - preempted_ns) / 1e6)
            _obs.record_instant(
                "serving.resumed", cat="serving",
                args={"rid": rid, "lane": slot, "resume_pos": m,
                      "priority": priority})
            self._publish_occupancy()
        return rid

    def _resume_key(self, seed, emitted):
        """The per-request sampling key chain, replayed host-side to
        its state after `emitted` tokens: admit() splits PRNGKey(seed)
        once for the first token, every decode step splits once more
        and carries split()[0] — so the carried key after `emitted`
        tokens is split applied `emitted` times. This is what makes a
        preempted sampled stream resume bit-exactly (zeros under
        greedy: the chain is never read)."""
        if self.greedy:
            return np.zeros((2,), np.uint32)
        key = jax.random.PRNGKey(seed)
        for _ in range(int(emitted)):
            key = jax.random.split(key)[0]
        return np.asarray(key, np.uint32)

    def _preempt_for(self, demand, priority):
        """Fund a `priority` admission short `demand` available blocks
        by preempting strictly-lower-priority lanes, lowest priority
        first and the YOUNGEST (largest rid) within a class — the
        cheapest prefix to throw away. Victims are captured into
        ``self.preempted`` as (Request, preempt_ns) with their synced
        token prefix intact (in-flight emissions discard by rid at
        sync, the cancel() rule) and their blocks — speculative draft
        over-allocation included — return to the pool via _free().
        Returns True when the demand is covered. A cheap upper bound
        (every victim's whole lifetime need) guards against preempting
        work that could not cover the demand anyway; a shared prefix
        block that outlives its sharer can still leave the greedy loop
        short, in which case the victims resume later and the
        admission simply fails this round."""
        if not self.paged:
            return False
        victims = [i for i, r in enumerate(self._slots)
                   if r is not None and r.priority < priority]
        bound = self._alloc.available \
            + sum(self._lane_need[i] for i in victims)
        if bound < demand:
            return False
        while self._alloc.available < demand:
            live = [(r.priority, -r.rid, i)
                    for i, r in enumerate(self._slots)
                    if r is not None and r.priority < priority]
            if not live:
                break
            _, _, i = min(live)
            req = self._slots[i]
            t_ns = time.perf_counter_ns()
            _obs.counter("serving.preemptions").add(1)
            if _obs.enabled():
                _obs.record_instant(
                    "serving.preempt", cat="serving",
                    args={"rid": req.rid, "lane": i,
                          "priority": req.priority,
                          "for_priority": priority,
                          "synced": req.emitted})
            if self._journal is not None:
                self._journal.append_park(req.rid, req.tokens,
                                          req.emitted)
            avail0 = self._alloc.available
            self._free(i)
            if _obs.enabled():
                _events.event(
                    "preempt", rid=req.rid, lane=i,
                    victim_priority=req.priority,
                    for_priority=priority, synced=req.emitted,
                    blocks_freed=self._alloc.available - avail0)
            self.preempted.append((req, t_ns))
        return self._alloc.available >= demand

    # ---- elastic KV pool (memory pressure) ----

    def shrink_pool(self, n):
        """Give back ``n`` blocks of KV capacity under memory pressure
        (the OOM shrink-and-retry path and the ``kv_shrink`` brownout
        rung both land here). Escalation order, cheapest first:
        park free capacity beyond the admission promises -> evict
        unreferenced prefix-cache blocks -> park the lowest-priority
        lane through the PR 11 preemption path (it lands on
        ``self.preempted`` and resumes bit-exactly via
        ``admit_continuation``). Returns the number of blocks actually
        parked (0 when not paged or nothing could be released)."""
        if not self.paged:
            return 0
        n = int(n)
        parked = self._alloc.shrink(n)
        while parked < n:
            need = n - parked
            self._evict_prefixes(need)     # best-effort; may be partial
            got = self._alloc.shrink(need)
            parked += got
            if got:
                continue
            live = [(r.priority, -r.rid, i)
                    for i, r in enumerate(self._slots) if r is not None]
            if not live:
                break
            _, _, i = min(live)
            req = self._slots[i]
            t_ns = time.perf_counter_ns()
            _obs.counter("serving.preemptions").add(1)
            if _obs.enabled():
                _obs.record_instant(
                    "serving.preempt", cat="serving",
                    args={"rid": req.rid, "lane": i,
                          "priority": req.priority,
                          "reason": "kv_shrink",
                          "synced": req.emitted})
            if self._journal is not None:
                self._journal.append_park(req.rid, req.tokens,
                                          req.emitted)
            avail0 = self._alloc.available
            self._free(i)
            if _obs.enabled():
                _events.event(
                    "preempt", rid=req.rid, lane=i,
                    victim_priority=req.priority,
                    reason="kv_shrink", synced=req.emitted,
                    blocks_freed=self._alloc.available - avail0)
            self.preempted.append((req, t_ns))
        if parked and _obs.enabled():
            _obs.counter("serving.kv_shrinks").add(1)
            _obs.record_instant(
                "serving.kv_shrink", cat="serving",
                args={"requested": n, "parked": parked,
                      "pool_parked": self._alloc.parked_blocks})
            _events.event("pool", op="shrink", requested=n,
                          parked=parked,
                          pool_parked=self._alloc.parked_blocks)
        return parked

    def grow_pool(self, n):
        """Return ``n`` blocks of KV capacity: unpark shrink-ledger
        blocks first, then physically extend the device pool (zero
        blocks appended to every leaf — existing ids and tables stay
        valid) for the remainder. Physical growth preflights its byte
        cost against live headroom and fires the ``kv.pool.grow``
        chaos site, so a grow under pressure fails loudly instead of
        wedging the device. Returns the number of blocks returned to
        circulation."""
        if not self.paged or int(n) <= 0:
            return 0
        n = int(n)
        if _chaos.enabled():
            _chaos.fire("kv.pool.grow", blocks=n)
        got = self._alloc.grow(n)
        rest = n - got
        if rest > 0:
            nbytes = tf.paged_cache_nbytes(self.cfg, rest,
                                           self.block_size)
            if getattr(self, "_dpool", None) is not None:
                nbytes += tf.paged_cache_nbytes(self.draft_cfg, rest,
                                                self.block_size)
            if _membudget.enabled():
                _membudget.preflight_bytes(
                    "kv.pool.grow", nbytes,
                    signature="%d+%d" % (self.num_blocks, rest))
            self._pool = tf.grow_paged_cache(self._pool, rest)
            if getattr(self, "_dpool", None) is not None:
                self._dpool = tf.grow_paged_cache(self._dpool, rest)
            self._alloc.extend(rest)
            self.num_blocks += rest
            got += rest
        if _obs.enabled():
            _obs.record_instant(
                "serving.kv_grow", cat="serving",
                args={"requested": n, "returned": got,
                      "num_blocks": self.num_blocks})
            _events.event("pool", op="grow", requested=n,
                          returned=got, num_blocks=self.num_blocks)
        return got

    def _oom_shrink(self, exc):
        """A decode dispatch hit RESOURCE_EXHAUSTED: classify it
        through the membudget taxonomy and respond with
        shrink-and-retry — park part of the pool and let the next
        ``step()`` redispatch against the smaller footprint — instead
        of the PR 6 lane-rebuild (which would throw away every lane's
        device state for what is a capacity problem, not a corruption
        problem). An injected chaos OOM fires BEFORE the jitted chunk
        consumes its donated carry, so lane state is intact; a real
        post-donation OOM that persists after the shrink falls through
        to the rebuild on the next consecutive failure. Returns True
        when the shrink released capacity (the caller skips the
        rebuild)."""
        _membudget.note_oom(self._chaos_site, exc)
        parked = self.shrink_pool(self._kv_shrink_blocks())
        return parked > 0

    def _brownout_admit_ok(self, priority):
        """The rung-3/5 admission gates (rungs 1-2 act on the decode
        and prefix paths, rung 4 on the pool, not here): rung 3
        throttles to one admission per scheduling round, rung 5 sheds
        the lowest priority class outright."""
        if self._bo_rung >= 5 and priority <= 0:
            if _obs.enabled():
                _obs.counter("serving.brownout_rejections").add(1)
            return False
        if self._bo_rung >= 3 and self._round_admits >= 1:
            return False
        return True

    def _kv_shrink_blocks(self):
        """How many blocks the kv_shrink rung parks
        (MXNET_MEM_KV_SHRINK_BLOCKS; default a quarter of the usable
        pool)."""
        v = _fastenv.get("MXNET_MEM_KV_SHRINK_BLOCKS")
        try:
            n = int(v) if v else 0
        except (TypeError, ValueError):
            n = 0
        return n if n > 0 else max((self.num_blocks - 1) // 4, 1)

    def _brownout_tick(self):
        """One controller evaluation per scheduling round: sustained
        SLO-attainment drop (below `brownout_attain`), block
        exhaustion, or (membudget-armed) device headroom below the
        reserve climbs one rung after `brownout_trip` consecutive bad
        rounds; `brownout_clear` consecutive healthy rounds walk one
        rung back down. The asymmetric streaks are the hysteresis — a
        single good round under churn must not bounce the ladder."""
        self._round_admits = 0
        bad = False
        if _slo.active():
            att = _slo.attainment()
            if att is not None and att < self._brownout_attain:
                bad = True
        if self.paged and self._alloc.available <= 0:
            bad = True
        if not bad and self.paged and _membudget.enabled():
            # proactive kv_shrink driver: act on the headroom gauge
            # BEFORE the allocator notices anything (the gauge moves
            # first when a co-located training job or snapshot eats
            # the device)
            hb = _membudget.headroom_bytes()
            if hb is not None and hb < _membudget.reserve_bytes():
                bad = True
        if bad:
            self._bo_good = 0
            self._bo_bad += 1
            if self._bo_bad >= self._brownout_trip \
                    and self._bo_rung < 5:
                self._bo_bad = 0
                self._set_rung(self._bo_rung + 1)
        else:
            self._bo_bad = 0
            self._bo_good += 1
            if self._bo_good >= self._brownout_clear \
                    and self._bo_rung > 0:
                self._bo_good = 0
                self._set_rung(self._bo_rung - 1)

    def _set_rung(self, rung):
        prev = self._bo_rung
        self._bo_rung = rung
        if self.paged:
            # the kv_shrink rung (4) parks part of the pool on the way
            # up and returns it on the way down — the proactive twin of
            # the OOM shrink-and-retry path
            if rung >= 4 and prev < 4 and not self._bo_parked:
                self._bo_parked = self.shrink_pool(
                    self._kv_shrink_blocks())
            elif rung < 4 and prev >= 4 and self._bo_parked:
                try:
                    self.grow_pool(self._bo_parked)
                    self._bo_parked = 0
                except Exception as exc:
                    # a grow that OOMs (real or injected) leaves the
                    # pool shrunk — correctness never depends on
                    # growing back, only capacity does
                    if not _membudget.is_resource_exhausted(exc):
                        raise
                    _membudget.note_oom("kv.pool.grow", exc)
        if _obs.enabled():
            _obs.gauge("serving.brownout_rung").set(rung)
            _obs.record_instant("serving.brownout", cat="serving",
                                args={"rung": rung})
            _events.event("brownout", frm=prev, to=rung)

    def _register_dispatch(self, kind, fn, args):
        """Attribution over the serving jit boundary: register this
        dispatch executable (once per signature) so its named scopes —
        the paged_decode_kernel / paged_verify_kernel megakernel rows
        under MXNET_PAGED_DECODE_PALLAS=1 — appear in ops summaries
        and the obs_regression kernel baseline guard."""
        import jax as _jax
        leaves = [a for a in _jax.tree_util.tree_leaves(args)
                  if hasattr(a, "shape")]
        sig = _obs_recompile.signature_of(leaves)
        origin = "serving.%s.%s" % (kind, self.name or "batcher")
        if sig and _attr.needs_program(origin, sig):
            _attr.register_program(origin, sig, fn, args)

    # ---- decode ----

    def step(self):
        """One scheduling step over all slots: `chunk_size` ragged
        decode steps in one device dispatch (one for the default
        chunk_size=1). Appends up to chunk_size tokens to every active
        request; returns {rid: full token list} for the requests that
        finished this step (their slots are freed). A request hitting
        its stop token or budget mid-chunk ends there — the lane's
        remaining in-chunk tokens are discarded and its slot frees at
        the chunk boundary.

        With pipeline_depth > 1 each step() keeps up to depth chunk
        dispatches in flight and syncs only the oldest one — same
        return contract, tokens arrive one dispatch later (bounded
        staleness; see the class docstring).

        With spec_k set each dispatch is a speculative draft/verify
        round (up to chunk_size * (spec_k + 1) tokens per lane per
        dispatch), pipelined the same way."""
        if self._spec_on:
            return self._step_spec()
        if self.pipeline_depth > 1:
            return self._step_pipelined()
        obs_on = _obs.enabled()
        finished = {}
        if self._pending_finished:
            # re-delivery of deduped already-finished streams (recover
            # and idempotency hits) rides the next step's return
            finished.update(self._pending_finished)
            self._pending_finished.clear()
        # retire requests already complete at admission (n_new=1, or a
        # stop token straight out of the prefill logits)
        for i, req in enumerate(self._slots):
            if req is not None and req.done:
                finished[req.rid] = list(req.tokens)
                if obs_on:
                    self._note_finish(req)
                self._note_done(req)
                self._free(i)
        if not any(s is not None for s in self._slots):
            self._end_round()
            return finished
        k = self.chunk_size
        try:
            if self.paged:
                self._ensure_coverage(k)
            # the synchronous dispatch blocks through the host fetch,
            # so one span covers dispatch + sync
            with _obs.span("serving.dispatch", cat="serving",
                           mode="sync", chunk=k,
                           lanes=self.active_count):
                if _chaos.enabled():
                    _chaos.fire(self._chaos_site, mode="sync")
                args = (self.params,)
                if self.paged:
                    args += (self._pool, self._tables)
                else:
                    args += (self._cache,)
                args += (jnp.asarray(self._tok),
                         jnp.asarray(self._pos),
                         jnp.asarray(self._keys))
                if k == 1:
                    fn = (_jitted_ragged_step_paged if self.paged
                          else _jitted_ragged_step)(
                        self.cfg, *self._controls)
                    if _membudget.enabled():
                        _membudget.preflight(self._chaos_site, fn,
                                             args)
                    if _attr.ops_enabled():
                        self._register_dispatch("decode", fn, args)
                    nxt, keys, state = fn(*args)
                    toks = np.asarray(nxt).astype(np.int32)[None]
                else:
                    fn = (_jitted_ragged_chunk_paged if self.paged
                          else _jitted_ragged_chunk)(
                        self.cfg, *self._controls, k)
                    if _membudget.enabled():
                        _membudget.preflight(self._chaos_site, fn,
                                             args)
                    if _attr.ops_enabled():
                        self._register_dispatch("decode", fn, args)
                    toks, keys, state = fn(*args)
                    toks = np.asarray(toks).astype(np.int32)   # [k, B]
                if self.paged:
                    self._pool = state
                else:
                    self._cache = state
        except Exception as exc:     # noqa: BLE001 — requeue-or-raise
            self._recover_dispatch_failure(exc)
            self._end_round()
            return finished
        self._dispatch_failures = 0
        self.dispatch_count += 1
        t_sync = time.perf_counter_ns() if obs_on else None
        # np.array (copy): asarray would give a READ-ONLY view of the
        # device buffer and the next admit()'s in-place key write fails
        self._keys = np.array(keys, np.uint32)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            grew = req.emitted
            for j in range(k):
                req.tokens.append(int(toks[j, i]))
                req.emitted += 1
                if req.done:
                    break
            grew = req.emitted - grew
            if self._journal is not None and grew:
                self._journal.append_emit(
                    req.rid, req.tokens[len(req.tokens) - grew:],
                    req.emitted)
            # the device advanced every lane k steps regardless of
            # where its request ended; mirror that here so a
            # CONTINUING lane's next chunk starts from the device's
            # true rolling state (freed lanes reset below)
            self._pos[i] += k
            self._tok[i] = toks[k - 1, i]
            if t_sync is not None:
                self._note_progress(req, i, grew, t_sync)
            if req.done:
                finished[req.rid] = list(req.tokens)
                if t_sync is not None:
                    self._note_finish(req, t_sync)
                self._note_done(req)
                self._free(i)
        if obs_on:
            self._publish_occupancy()
        self._end_round()
        return finished

    def _end_round(self):
        """Per-scheduling-round epilogue shared by every step path:
        the brownout controller's tick, the MXNET_SERVING_DEBUG
        idle-point allocator audit, and the MXNET_MEM_GAUGE_EVERY
        device-memory gauge cadence. One guarded branch each when
        off."""
        if self.brownout:
            self._brownout_tick()
        if self._debug:
            self._debug_idle_check()
        if self._journal is not None:
            self._journal.maybe_gc()
        if _obs.enabled():
            if self._journal is not None:
                _obs.gauge("serving.journal_depth_bytes").set(
                    self._journal.depth_bytes)
                _obs.gauge("serving.journal_lag_records").set(
                    self._journal.lag_records)
            from .. import storage as _storage
            _storage.maybe_publish_device_memory_gauges()

    # ---- pipelined scheduling (pipeline_depth > 1) ----

    def _step_pipelined(self):
        """One pipelined scheduling step: top the dispatch window up
        to `pipeline_depth` chunks (each issued against the previous
        dispatch's device-resident carry — no host sync between
        them), then sync ONLY the oldest chunk's emissions. The
        synchronous round trip that gates every chunk at depth 1 thus
        amortizes over `depth` chunks, which is the whole lever when
        the chip sits behind a network tunnel (docs/SERVING.md)."""
        obs_on = _obs.enabled()
        finished = {}
        if self._pending_finished:
            # re-delivery of deduped already-finished streams (recover
            # and idempotency hits) rides the next step's return
            finished.update(self._pending_finished)
            self._pending_finished.clear()
        # retire requests already complete at admission (n_new=1, or a
        # stop token straight out of the prefill logits)
        for i, req in enumerate(self._slots):
            if req is not None and req.done:
                finished[req.rid] = list(req.tokens)
                if obs_on:
                    self._note_finish(req)
                self._note_done(req)
                self._free(i)
        while (len(self._inflight) < self.pipeline_depth
               and any(s is not None for s in self._slots)):
            try:
                self._dispatch_chunk()
            except Exception as exc:  # noqa: BLE001 — requeue-or-raise
                self._recover_dispatch_failure(exc)
                self._end_round()
                return finished
        if self._inflight:
            finished.update(self._sync_oldest())
        if not any(s is not None for s in self._slots):
            # nothing live: the remaining in-flight chunks only advance
            # parked lanes, so their emissions belong to no request —
            # drop the records (the device work itself is already
            # queued and harmless)
            self._inflight.clear()
        self._end_round()
        return finished

    def _dispatch_chunk(self):
        """Issue one chunk against the device-resident carry and
        snapshot which request owned each lane at dispatch time — the
        identity that decides, at sync, whose stream each lane's
        emissions belong to (a lane re-admitted mid-flight discards
        the old occupant's in-flight tokens by rid mismatch)."""
        if self.paged:
            self._ensure_coverage(self.chunk_size)
        with _obs.span("serving.dispatch", cat="serving",
                       depth=len(self._inflight) + 1):
            if _chaos.enabled():
                _chaos.fire(self._chaos_site, mode="pipelined",
                            depth=len(self._inflight) + 1)
            if self.paged:
                args = (self.params, self._pool, self._tables,
                        self._dev_tok, self._dev_pos, self._dev_keys)
                if _membudget.enabled():
                    _membudget.preflight(self._chaos_site,
                                         self._pipe_fn, args)
                if _attr.ops_enabled():
                    self._register_dispatch("pipeline", self._pipe_fn,
                                            args)
                toks, pool, tables, tok, pos, keys = \
                    self._pipe_fn(*args)
                self._pool, self._tables = pool, tables
            else:
                args = (self.params, self._cache, self._dev_tok,
                        self._dev_pos, self._dev_keys)
                if _membudget.enabled():
                    _membudget.preflight(self._chaos_site,
                                         self._pipe_fn, args)
                toks, cache, tok, pos, keys = self._pipe_fn(*args)
                self._cache = cache
        self._dispatch_failures = 0
        self.dispatch_count += 1
        if self.paged:
            # every lane's device position advances k per chunk —
            # mirror it so the NEXT dispatch's coverage is exact
            self._sched_pos += self.chunk_size
        self._dev_tok, self._dev_pos, self._dev_keys = tok, pos, keys
        self._inflight.append(
            (toks, [r.rid if r is not None else None
                    for r in self._slots]))
        if _obs.enabled():
            _obs.gauge("serving.inflight_depth").set(
                len(self._inflight))
            self._publish_occupancy()

    def _sync_oldest(self):
        """Fetch the oldest in-flight chunk's emissions and credit
        them to the requests that owned each lane when it was
        DISPATCHED (and still do): evicted or re-admitted lanes are
        discarded, a request ending mid-chunk keeps only its prefix.
        This is the only host-blocking point of the pipelined loop."""
        toks_dev, lanes = self._inflight.popleft()
        with _obs.span("serving.sync", cat="serving",
                       behind=len(self._inflight)):
            toks = np.asarray(toks_dev).astype(np.int32)     # [k, B]
        obs_on = _obs.enabled()
        t_sync = time.perf_counter_ns() if obs_on else None
        finished = {}
        for i, rid in enumerate(lanes):
            if rid is None:
                continue
            req = self._slots[i]
            if req is None or req.rid != rid or req.done:
                continue               # canceled / replaced mid-flight
            grew = req.emitted
            for j in range(toks.shape[0]):
                req.tokens.append(int(toks[j, i]))
                req.emitted += 1
                if req.done:
                    break
            if self._journal is not None and req.emitted > grew:
                self._journal.append_emit(
                    req.rid, req.tokens[grew - req.emitted:],
                    req.emitted)
            if t_sync is not None:
                self._note_progress(req, i, req.emitted - grew, t_sync)
            if req.done:
                finished[req.rid] = list(req.tokens)
                if t_sync is not None:
                    self._note_finish(req, t_sync)
                self._note_done(req)
                self._free(i)
        if obs_on:
            self._publish_occupancy()
        return finished

    # ---- speculative scheduling (spec_k set) ----

    def _step_spec(self):
        """One speculative scheduling step: top the in-flight window up
        to `pipeline_depth` draft/verify dispatches (depth 1 means the
        classic dispatch-then-sync round trip, just k+1 wide per lane
        per round), then sync only the oldest. Identical skeleton to
        _step_pipelined — per-lane emissions were ALREADY ragged there,
        speculation only makes the raggedness data-dependent."""
        obs_on = _obs.enabled()
        finished = {}
        if self._pending_finished:
            # re-delivery of deduped already-finished streams (recover
            # and idempotency hits) rides the next step's return
            finished.update(self._pending_finished)
            self._pending_finished.clear()
        # retire requests already complete at admission (n_new=1, or a
        # stop token straight out of the prefill logits)
        for i, req in enumerate(self._slots):
            if req is not None and req.done:
                finished[req.rid] = list(req.tokens)
                if obs_on:
                    self._note_finish(req)
                self._note_done(req)
                self._free(i)
        while (len(self._inflight) < self.pipeline_depth
               and any(s is not None for s in self._slots)):
            try:
                self._dispatch_spec()
            except Exception as exc:  # noqa: BLE001 — requeue-or-raise
                self._recover_dispatch_failure(exc)
                self._end_round()
                return finished
        if self._inflight:
            finished.update(self._sync_oldest_spec())
        if not any(s is not None for s in self._slots):
            # nothing live: in-flight emissions belong to no request
            self._inflight.clear()
        self._end_round()
        return finished

    def _dispatch_spec(self):
        """Issue one speculative dispatch (chunk_size draft/verify
        rounds) against the device-resident carry. Paged coverage is
        reserved for the WORST case — every lane accepting every draft
        every round — and the sync reconciles `_sched_pos` down to the
        measured acceptance, releasing the over-reserved draft blocks
        (see _reconcile_sched_pos)."""
        worst = self.chunk_size * (self.spec_k + 1)
        if self.paged:
            self._ensure_coverage(worst)
        # brownout rung 1+: clamp the draft width to 1 — verify cost
        # collapses toward plain decode while the ladder is engaged,
        # and the adaptive controller takes back over on recovery
        keff_np = (np.minimum(self._keff, 1)
                   if self.brownout and self._bo_rung >= 1
                   else self._keff)
        keff = jnp.asarray(keff_np)
        with _obs.span("serving.dispatch", cat="serving", mode="spec",
                       depth=len(self._inflight) + 1,
                       spec_k=self.spec_k):
            if _chaos.enabled():
                _chaos.fire(self._chaos_site, mode="spec",
                            depth=len(self._inflight) + 1)
            if self._spec_provider == "ngram":
                if self.paged:
                    args = (self.params, self._pool, self._tables,
                            self._dev_hist, self._dev_tok,
                            self._dev_pos, keff)
                else:
                    args = (self.params, self._cache,
                            self._dev_hist, self._dev_tok,
                            self._dev_pos, keff)
            elif self.paged:
                args = (self.params, self.draft_params, self._pool,
                        self._dpool, self._tables, self._dev_tok,
                        self._dev_pos, keff)
            else:
                args = (self.params, self.draft_params, self._cache,
                        self._dcache, self._dev_tok, self._dev_pos,
                        keff)
            if _membudget.enabled():
                _membudget.preflight(self._chaos_site, self._spec_fn,
                                     args)
            if _attr.ops_enabled():
                self._register_dispatch("spec", self._spec_fn, args)
            if self._spec_provider == "ngram":
                if self.paged:
                    targets, emits, pool, hist, tok, pos = \
                        self._spec_fn(*args)
                    self._pool = pool
                else:
                    targets, emits, cache, hist, tok, pos = \
                        self._spec_fn(*args)
                    self._cache = cache
                self._dev_hist = hist
            elif self.paged:
                targets, emits, pool, dpool, tok, pos = \
                    self._spec_fn(*args)
                self._pool, self._dpool = pool, dpool
            else:
                targets, emits, cache, dcache, tok, pos = \
                    self._spec_fn(*args)
                self._cache, self._dcache = cache, dcache
        self._dispatch_failures = 0
        self.dispatch_count += 1
        if self.paged:
            # worst-case position mirror so the NEXT dispatch's
            # coverage is sufficient whatever this one accepts; the
            # sync subtracts the measured shortfall back out
            self._sched_pos += worst
        self._dev_tok, self._dev_pos = tok, pos
        self._inflight.append(
            (targets, emits,
             [r.rid if r is not None else None for r in self._slots],
             np.array(keff_np)))
        if _obs.enabled():
            _obs.gauge("serving.inflight_depth").set(
                len(self._inflight))
            self._publish_occupancy()

    def _sync_oldest_spec(self):
        """Fetch the oldest speculative dispatch's verified targets and
        emit counts, credit each lane's ACCEPTED tokens to the request
        that owned it at dispatch time (rid snapshot, exactly the
        pipelined rule), feed the measured acceptance into the per-lane
        EWMA the adaptive-k controller reads, and reconcile paged
        block accounting down from worst case."""
        targets_dev, emits_dev, lanes, keffs = self._inflight.popleft()
        with _obs.span("serving.sync", cat="serving", mode="spec",
                       behind=len(self._inflight)):
            targets = np.asarray(targets_dev)      # [rounds, B, k+1]
            emits = np.asarray(emits_dev).astype(np.int64)  # [rounds, B]
        obs_on = _obs.enabled()
        t_sync = time.perf_counter_ns() if obs_on else None
        finished = {}
        rounds = emits.shape[0]
        for i, rid in enumerate(lanes):
            if rid is None:
                continue
            req = self._slots[i]
            if req is None or req.rid != rid or req.done:
                continue               # canceled / replaced mid-flight
            grew0 = req.emitted
            # keff at DISPATCH time: the width these rounds actually
            # drafted at, the denominator of their acceptance ratio
            keff_i = max(int(keffs[i]), 1)
            for r in range(rounds):
                e = int(emits[r, i])
                acc = e - 1            # accepted drafts this round
                self._spec_rounds += 1
                self._spec_drafted += keff_i
                self._spec_accepted += acc
                self._accept_ewma[i] += _SPEC_EWMA_ALPHA * (
                    acc / keff_i - self._accept_ewma[i])
                if obs_on:
                    _obs.histogram("serving.spec_accept_len",
                                   "tokens").observe(acc)
                for j in range(e):
                    req.tokens.append(int(targets[r, i, j]))
                    req.emitted += 1
                    if req.done:
                        break
                if req.done:
                    break
            if self._journal is not None and req.emitted > grew0:
                self._journal.append_emit(
                    req.rid, req.tokens[grew0 - req.emitted:],
                    req.emitted)
            if self.spec_accept_floor > 0.0:
                # per-lane adaptive k: measured acceptance under the
                # floor shrinks the draft width (never below 1 — one
                # draft still doubles the best-case tokens/dispatch),
                # at-or-above grows it back toward spec_k
                k0 = int(self._keff[i])
                if self._accept_ewma[i] < self.spec_accept_floor:
                    self._keff[i] = max(1, k0 - 1)
                else:
                    self._keff[i] = min(self.spec_k, k0 + 1)
                if int(self._keff[i]) != k0 and _obs.enabled():
                    _events.event(
                        "spec_k", lane=i, frm=k0,
                        to=int(self._keff[i]),
                        accept=round(float(self._accept_ewma[i]), 4))
            if t_sync is not None:
                self._note_progress(req, i, req.emitted - grew0,
                                    t_sync)
            if req.done:
                finished[req.rid] = list(req.tokens)
                if t_sync is not None:
                    self._note_finish(req, t_sync)
                self._note_done(req)
                self._free(i)
        if self.paged:
            self._reconcile_sched_pos(emits, lanes)
        if obs_on:
            _obs.gauge("serving.spec_draft_ratio").set(
                self._spec_accepted / max(self._spec_drafted, 1))
            self._publish_occupancy()
        return finished

    def _reconcile_sched_pos(self, emits, lanes):
        """Walk `_sched_pos` back from the dispatch-time worst case to
        the measured per-lane advance and release the block tail the
        lane over-reserved for drafts it did not accept. Only lanes
        whose occupant is UNCHANGED since dispatch (rid snapshot
        matches) reconcile — a freed or re-admitted lane's patch
        already reset its accounting authoritatively."""
        worst = self.chunk_size * (self.spec_k + 1)
        advance = emits.sum(axis=0)
        for i, rid in enumerate(lanes):
            if rid is None:
                continue
            req = self._slots[i]
            if req is None or req.rid != rid:
                continue
            self._sched_pos[i] -= worst - int(advance[i])
            self._trim_lane_blocks(i)

    def _trim_lane_blocks(self, i):
        """Release lane i's allocated blocks beyond its reconciled
        coverage, converting them back into reservation (the lane's
        lifetime need is unchanged — the blocks were just materialized
        early for a worst case that did not happen). Safe against
        in-flight dispatches: their writes are bounded by the KEPT
        coverage (every dispatch's worst case beyond the synced one is
        still counted in _sched_pos), and a trimmed block's positions
        sit above every in-flight query position, so stale table
        snapshots can only reach it through masked-out attention rows.
        Trimmed blocks are always refcount-1: sharing only ever covers
        prompt-prefix blocks, which reconciled coverage never drops."""
        bs = self.block_size
        keep = min(max(int(self._sched_pos[i]) - 1, 0) // bs,
                   self._lane_need[i] - 1) + 1
        blocks = self._lane_blocks[i]
        while len(blocks) > max(keep, 1):
            bid = blocks.pop()
            self._tables = _jitted_table_entry(self.cfg)(
                self._tables, jnp.int32(i), jnp.int32(len(blocks)),
                jnp.int32(0))
            self._alloc.release([bid])
            self._alloc.reserve(1)

    def _spec_admit(self, slot, ctx, t_p, first):
        """Seed lane `slot`'s draft state for a stream whose cache-
        resident prefix is the `t_p` tokens `ctx`, with `first` the
        lane's current token at position t_p. The n-gram provider gets
        its stream-history row (prefix + current token); the model
        provider gets a full draft-model prefill over the prefix, so
        draft steps and target verifies walk positions in lockstep
        (and, under paging, the same block tables)."""
        if self._spec_provider == "ngram":
            row = np.zeros((self.cfg.max_len,), np.int32)
            row[:t_p] = ctx
            row[t_p] = first           # t_p < max_len: n_new >= 1
            with _obs.span("serving.patch", cat="serving",
                           kind="spec_hist", lane=slot):
                self._dev_hist = self._hist_fn(
                    self._dev_hist, jnp.int32(slot), jnp.asarray(row))
            return
        drow = tf.init_cache(self.draft_cfg, 1)
        width = min(_bucket(t_p), self.draft_cfg.max_len)
        padded = np.zeros((1, width), np.int32)
        padded[0, :t_p] = ctx
        with _obs.span("serving.prefill", cat="serving", kind="draft",
                       lane=slot, prompt_tokens=t_p):
            _, drow = tf._jitted_prefill_chunk_row(self.draft_cfg)(
                self.draft_params, drow, jnp.asarray(padded),
                jnp.int32(0), jnp.int32(t_p - 1))
            if self.paged:
                # the lane's freshly mapped blocks (all of them —
                # model-draft paging never shares a prefix, see
                # admit()) receive the draft rows whole-block
                own = self._lane_blocks[slot]
                self._dpool = _jitted_block_write(
                    self.draft_cfg, len(own))(
                        self._dpool, drow,
                        jnp.asarray(own, jnp.int32), jnp.int32(0))
            else:
                self._dcache = _jitted_slot_write(self.draft_cfg)(
                    self._dcache, drow, jnp.int32(slot))

    # ---- dispatch-failure recovery ----

    def _recover_dispatch_failure(self, exc):
        """A decode dispatch raised (injected fault, transient XLA
        failure). The jitted chunk donates its carry, so whatever it
        consumed is gone — rebuild the pool from scratch and REQUEUE
        every live request from its synced token state: lanes freed,
        carry re-zeroed, each request re-prefilled at its current
        prefix. Greedy streams continue bit-exactly (decode is a pure
        function of the token prefix); sampled streams continue on a
        deterministically reseeded chain (the in-flight key chain died
        with the carry). After ``_max_dispatch_failures`` consecutive
        failures the error re-raises — a deterministic fault must not
        loop as an infinite requeue."""
        self._dispatch_failures += 1
        if _obs.enabled():
            _obs.counter("serving.dispatch_failures").add(1)
            _obs.record_instant(
                "serving.dispatch_failed", cat="serving",
                args={"error": "%s: %s" % (type(exc).__name__, exc),
                      "consecutive": self._dispatch_failures})
        if self._dispatch_failures > self._max_dispatch_failures:
            raise exc
        if self.paged and _membudget.is_resource_exhausted(exc) \
                and self._oom_shrink(exc):
            # memory pressure, not corruption: the pool shrank and the
            # lanes are intact — the next step() retries as-is
            return
        pending = [r for r in self._slots if r is not None]
        self._rebuild_state()
        for req in pending:
            self._readmit(req)

    def _rebuild_state(self):
        """Rebuild every piece of device + scheduling state from
        scratch: slots emptied, pool/cache re-initialized, carry
        re-zeroed, allocator and prefix cache reset. Shared by the
        dispatch-failure requeue path (which then re-admits the live
        requests) and reset_lanes() (which drops them)."""
        self._slots = [None] * self.max_batch
        if self.paged:
            # the donated pool died with the dispatch — and the prefix
            # cache's blocks lived in it, so those entries die too
            # (re-cache_prefix() after recovery to restore sharing)
            self._pool = tf.init_paged_cache(self.cfg, self.num_blocks,
                                             self.block_size)
            self._tables = jnp.zeros((self.max_batch, self._nb),
                                     jnp.int32)
            self._alloc = BlockAllocator(self.num_blocks)
            self._lane_blocks = [[] for _ in range(self.max_batch)]
            self._lane_need = [0] * self.max_batch
            self._sched_pos = np.zeros((self.max_batch,), np.int64)
            self._prefix_cache.clear()
            # the fresh allocator parks nothing: the brownout ledger
            # must agree, or its walk-down would grow past the
            # original pool
            self._bo_parked = 0
        else:
            self._cache = tf.init_cache(self.cfg, self.max_batch)
        self._pos = np.zeros((self.max_batch,), np.int32)
        self._tok = np.zeros((self.max_batch,), np.int32)
        self._keys = np.zeros((self.max_batch, 2), np.uint32)
        if self._device_carry:
            self._inflight.clear()
            self._dev_tok = jnp.zeros((self.max_batch,), jnp.int32)
            self._dev_pos = jnp.zeros((self.max_batch,), jnp.int32)
            self._dev_keys = jnp.zeros((self.max_batch, 2), jnp.uint32)
        if self._spec_on:
            # the donated draft state died with the failed dispatch;
            # re-admission re-seeds each live lane's slice of it
            self._keff[:] = self.spec_k
            self._accept_ewma[:] = 1.0
            if self._spec_provider == "ngram":
                self._dev_hist = jnp.zeros(
                    (self.max_batch, self.cfg.max_len), jnp.int32)
            elif self.paged:
                self._dpool = tf.init_paged_cache(
                    self.draft_cfg, self.num_blocks, self.block_size)
            else:
                self._dcache = tf.init_cache(self.draft_cfg,
                                             self.max_batch)

    def reset_lanes(self):
        """Abandon every live request and rebuild the batcher to its
        just-constructed state (fresh pool, empty slots, zeroed carry,
        cleared failure count). The circuit-breaker revival path uses
        this to give a replica whose dispatch state may be poisoned a
        clean slate before routing its HALF-OPEN canary — the dead
        replica's requests were already drained to the router, so
        nothing live is lost. Raises whatever the device raises if the
        rebuild itself fails (the replica stays broken)."""
        self._rebuild_state()
        self._dispatch_failures = 0
        self.preempted = []
        self._bo_rung = self._bo_bad = self._bo_good = 0
        self._bo_parked = 0     # the rebuilt allocator parks nothing
        self._round_admits = 0
        if _obs.enabled():
            _obs.record_instant("serving.reset_lanes", cat="serving")

    def _readmit(self, req):
        """Put a live request back into a (guaranteed free) lane from
        its token history: the cache is re-prefilled over everything
        but the last token, and decode resumes feeding that last token
        at its true position — the standard continuation identity
        (cache holds keys for tokens[:-1], tok=tokens[-1],
        pos=len-1)."""
        slot = next(i for i, s in enumerate(self._slots) if s is None)
        ctx, last = req.tokens[:-1], req.tokens[-1]
        m = len(ctx)
        assert m >= 1, "a live request always has prompt + first token"
        row_cache = tf.init_cache(self.cfg, 1)
        width = min(_bucket(m), self.cfg.max_len)
        padded = np.zeros((1, width), np.int32)
        padded[0, :m] = ctx
        _, row_cache = tf._jitted_prefill_chunk_row(self.cfg)(
            self.params, row_cache, jnp.asarray(padded),
            jnp.int32(0), jnp.int32(m - 1))
        if self.greedy:
            key_np = np.zeros((2,), np.uint32)
        else:
            key_np = np.asarray(jax.random.fold_in(
                jax.random.PRNGKey(req.seed), req.emitted), np.uint32)
        if self.paged:
            # remaining lifetime from the resume point (the fresh
            # allocator always fits what the old pool held — prefix
            # sharing died with it, but each request's own demand was
            # admission-checked without assuming sharing survives a
            # pool rebuild)
            total = len(req.tokens) + (req.n_new - req.emitted)
            lifetime, init_n = self._block_math(m, total)
            self._paged_map_lane(slot, m, row_cache, 0, [], lifetime,
                                 init_n)
        else:
            self._cache = _jitted_slot_write(self.cfg)(
                self._cache, row_cache, jnp.int32(slot))
        if self._device_carry:
            self._dev_tok, self._dev_pos, self._dev_keys = \
                self._patch_fn(self._dev_tok, self._dev_pos,
                               self._dev_keys, jnp.int32(slot),
                               jnp.int32(last), jnp.int32(m),
                               jnp.asarray(key_np))
        else:
            self._pos[slot] = m
            self._tok[slot] = last
            self._keys[slot] = key_np
        if self._spec_on:
            # re-seed the lane's draft state from the synced prefix —
            # the requeue resumes exactly like a fresh admission whose
            # prompt is everything synced so far
            self._spec_admit(slot, ctx, m, last)
        self._slots[slot] = req
        if _obs.enabled():
            _obs.record_instant("serving.requeued", cat="serving",
                                args={"rid": req.rid, "lane": slot,
                                      "resume_pos": m})
            # keep the request's flow chain alive across the requeue so
            # the trace ties pre-failure decode to the resumed lane
            _obs.record_flow("serving.request", req.rid, "t",
                             cat="serving",
                             args={"rid": req.rid, "lane": slot,
                                   "requeued": True})

    # ---- durability: crash recovery + weight hot-swap ----

    def recover(self):
        """Replay the attached journal after a process crash and
        re-enter every request it recorded.

        Finished requests (tombstone reason ``finish``, or a live
        record whose stream was already complete when the process
        died) are served from their recorded emissions — staged into
        the next step()'s return — and repopulate the idempotency
        window, so a client's re-submit dedups instead of recomputing.
        Live requests re-enter as continuations from their journaled
        synced prefix and resume BIT-exactly (greedy and sampled: the
        submit record carries the sampling seed and the synced count,
        and ``_resume_key`` replays the key chain). A live record that
        does not fit the current pool is parked on ``self.preempted``
        exactly like a PR 14 preemption victim — run()/the router
        resumes it when a lane frees.

        Returns ``(resumed, finished, skipped)``: old rid -> new rid
        (None = parked), rid -> final tokens, and the journal's
        skipped-record evidence (torn tail, CRC mismatch — each
        ``{"segment", "record", "reason"}``)."""
        if self._journal is None:
            raise RuntimeError(
                "recover() needs a journal attached "
                "(MXNET_SERVING_JOURNAL_DIR or journal=)")
        live, fin, skipped = self._journal.replay()
        # fresh-process rids must not collide with journaled ones: a
        # replayed fin for rid N must never tombstone a NEW request
        self._next_rid = max(self._next_rid,
                             self._journal.max_rid + 1)
        done = {}
        for rid, rec in fin.items():
            done[rid] = list(rec["tokens"])
            if rec.get("key") is not None:
                self._idem_done[rec["key"]] = (rid, list(rec["tokens"]))
        resumed = {}
        for rid in sorted(live):
            rec = live[rid]
            toks = list(rec["tokens"])
            emitted = int(rec["emitted"])
            n_more = int(rec["n_new"]) - emitted
            stop = rec["stop"]
            if emitted >= 1 and (n_more <= 0 or
                                 (stop is not None and toks
                                  and toks[-1] == stop)):
                # crashed after the final emission landed but before
                # the fin record did: the stream is complete — serve
                # it and write the tombstone now
                done[rid] = list(toks)
                if rec.get("key") is not None:
                    self._idem_done[rec["key"]] = (rid, list(toks))
                self._journal.append_finish(rid, "finish", tokens=toks)
                continue
            if emitted == 0:
                # never emitted (a router-side queue record): a fresh
                # admission replays the whole prompt
                new = self.admit(toks, rec["n_new"], seed=rec["seed"],
                                 stop_token=stop,
                                 priority=rec["prio"],
                                 key=rec.get("key"))
                if new is not None:
                    self._journal.append_finish(rid, "resume")
                resumed[rid] = new
                continue
            new = self.admit_continuation(
                toks, n_more, seed=rec["seed"], emitted=emitted,
                stop_token=stop, priority=rec["prio"],
                resumes=rid, key=rec.get("key"))
            if new is None:
                # capacity-blocked: park it like a preemption victim
                # (its journal record stays live, so a second crash
                # before it resumes still recovers it)
                req = Request(rid, toks, rec["n_new"], stop,
                              seed=rec["seed"],
                              priority=rec["prio"],
                              key=rec.get("key"))
                req.emitted = emitted
                self.preempted.append((req, time.perf_counter_ns()))
            resumed[rid] = new
        self._pending_finished.update(done)
        if _obs.enabled():
            _obs.counter("serving.journal_recoveries").add(1)
            _obs.record_instant(
                "serving.recover", cat="serving",
                args={"resumed": len(resumed), "finished": len(done),
                      "skipped": len(skipped)})
            _events.event("recover", resumed=len(resumed),
                          finished=len(done), skipped=len(skipped))
        return resumed, done, skipped

    def swap_weights(self, params, manifest=None):
        """Hot-swap the served weights without dropping a request.

        ``manifest`` gates the swap on PR 13's lineage machinery:
        a checkpoint-directory path runs ``verify_lineage`` (the
        newest retained manifest must verify) and reads its
        ``param_fingerprint``; a manifest dict supplies the
        fingerprint directly; None skips verification (rollback to an
        already-served params object). The incoming tree's recomputed
        fingerprint must MATCH — mismatched weights raise
        ``CheckpointCorrupt`` and the old params keep serving.

        HBM preflight (PR 14 membudget): old + new params are resident
        together during the swap; when that does not fit the budget the
        swap degrades to drain-then-swap (the old reference is dropped
        at the quiesce point before the new one is installed —
        ``mode="drain"`` in the result).

        The swap quiesces at a dispatch boundary: in-flight chunks are
        synced (their emissions deliver through the next step()), live
        lanes are captured, device state is rebuilt against the new
        params, and every live request re-enters through ``_readmit``
        — same continuation identity as the dispatch-failure requeue,
        so streams continue under the new weights with their synced
        prefixes intact. Returns ``{"fingerprint", "previous",
        "mode"}``."""
        from . import checkpoint as _ckpt
        want = None
        if isinstance(manifest, str):
            chain = _ckpt.verify_lineage(manifest)
            if not chain or chain[0]["status"] != "verified":
                raise _ckpt.CheckpointCorrupt(
                    "swap_weights: lineage of %s does not verify (%s)"
                    % (manifest,
                       chain[0]["status"] if chain else "no manifests"))
            with open(os.path.join(manifest, chain[0]["name"])) as f:
                want = json.load(f).get("param_fingerprint")
        elif isinstance(manifest, dict):
            want = manifest.get("param_fingerprint")
        new_fp = _integrity.params_fingerprint(params)
        if want is not None and new_fp != want:
            raise _ckpt.CheckpointCorrupt(
                "swap_weights: incoming parameter fingerprint %s does "
                "not match manifest %s — refusing unverified weights"
                % (new_fp, want))
        if _chaos.enabled():
            _chaos.fire("serving.swap", fingerprint=new_fp)
        mode = "resident"
        if _membudget.enabled():
            try:
                ok = _membudget.preflight_bytes(
                    "serving.swap", _membudget.tree_nbytes(params),
                    signature=new_fp)
            except _membudget.MemoryBudgetExceeded:
                ok = False
            if not ok:
                mode = "drain"
        prev_fp = self.weight_fingerprint
        # quiesce: sync every in-flight dispatch so no chunk computed
        # under the old weights lands after the swap (its emissions
        # deliver through _pending_finished at the next step())
        inflight = getattr(self, "_inflight", None)
        if inflight:
            sync = (self._sync_oldest_spec if self._spec_on
                    else self._sync_oldest)
            while inflight:
                self._pending_finished.update(sync())
        pending = [r for r in self._slots if r is not None]
        if mode == "drain":
            # drop the old reference before materializing against the
            # new one — the degraded path for budgets that cannot hold
            # both trees resident
            self.params = None
        self.params = params
        self._weight_fp = None
        if pending or self.paged or self._device_carry:
            # the cache/pool holds K/V computed under the OLD weights:
            # rebuild from scratch and re-prefill every live request
            # under the new ones (same path as the dispatch-failure
            # requeue)
            self._rebuild_state()
            for req in pending:
                self._readmit(req)
        else:
            self._prefix_cache.clear()
        new_fp = self.weight_fingerprint
        _obs.counter("serving.weight_swaps").add(1)
        if _obs.enabled():
            _obs.record_instant(
                "serving.swap", cat="serving",
                args={"fingerprint": new_fp, "previous": prev_fp,
                      "mode": mode, "live": len(pending)})
            _events.event("swap", fingerprint=new_fp,
                          previous=prev_fp, mode=mode,
                          live=len(pending))
        return {"fingerprint": new_fp, "previous": prev_fp,
                "mode": mode}

    def cancel(self, rid):
        """Evict a request mid-decode (client disconnect, timeout):
        frees its slot immediately for the next admission. Returns the
        tokens emitted so far, or None when `rid` is not active (never
        admitted, finished, or already canceled). The other lanes'
        streams are untouched — eviction only parks the slot. Under
        pipelining "so far" means synced so far: tokens the lane
        emitted in still-in-flight chunks are discarded at their sync
        (rid mismatch), like any mid-flight identity change."""
        for i, req in enumerate(self._slots):
            if req is not None and req.rid == rid:
                out = list(req.tokens)
                if _obs.enabled():
                    self._note_finish(req, evicted=True)
                self._note_done(req, reason="cancel")
                self._free(i)
                return out
        return None

    def _free(self, i):
        """Free slot i. Idle lanes keep decoding (static batch shape);
        parking them at position 0 means their garbage K/V lands where
        the next admission's prefill overwrites it — defense in depth
        on top of the `attention <= pos` self-healing argument. Under
        pipelining the park is a device-side lane patch sequenced
        after the in-flight chunks (whose writes to this lane are the
        already-harmless idle-lane garbage)."""
        self._slots[i] = None
        if self.paged:
            # return the lane's references (a shared prefix block
            # frees only when its LAST sharer lets go) and the unused
            # tail of its reservation, then park the table on the
            # null block — in-flight chunks still write through their
            # dispatch-time tables, whole-block overwrites on
            # reallocation make that harmless
            blocks = self._lane_blocks[i]
            self._alloc.release(blocks)
            self._alloc.unreserve(self._lane_need[i] - len(blocks))
            self._lane_blocks[i] = []
            self._lane_need[i] = 0
            self._sched_pos[i] = 0
            self._tables = _jitted_table_row(self.cfg)(
                self._tables, jnp.int32(i),
                jnp.zeros((self._nb,), jnp.int32))
        if self._device_carry:
            with _obs.span("serving.patch", cat="serving", kind="park",
                           lane=i):
                self._dev_tok, self._dev_pos, self._dev_keys = \
                    self._patch_fn(self._dev_tok, self._dev_pos,
                                   self._dev_keys, jnp.int32(i),
                                   jnp.int32(0), jnp.int32(0),
                                   jnp.zeros((2,), jnp.uint32))
        else:
            self._pos[i] = 0
            self._tok[i] = 0
        if self._spec_on:
            # reset the adaptive-k controller for the next occupant
            # (the hist row / draft cache need no clearing — the next
            # admission's _spec_admit overwrites them whole)
            self._keff[i] = self.spec_k
            self._accept_ewma[i] = 1.0

    # ---- request-level observability ----
    # Every caller guards on _obs.enabled(): with telemetry off none of
    # these run and the batcher pays exactly the guarded branches.

    def _note_admit(self, req, lane, t_admit_ns, enqueued_ns):
        """Admission bookkeeping: queue-wait span + histogram, TTFT
        histogram, and the flow-chain start."""
        t1 = time.perf_counter_ns()
        req.t_enq_ns = enqueued_ns
        req.t_admit_ns = t_admit_ns
        req.t_first_ns = req.t_last_ns = t1
        if self._t_serve_start_ns is None:
            self._t_serve_start_ns = t_admit_ns
        if enqueued_ns is not None:
            q_ms = (t_admit_ns - enqueued_ns) / 1e6
            _obs.record_span("serving.queue_wait", "serving",
                             enqueued_ns, t_admit_ns,
                             {"rid": req.rid})
            _obs.histogram("serving.queue_ms", "ms").observe(q_ms)
            if _slo.check("queue_ms", q_ms):
                req.slo_bad = True
        # TTFT from enqueue when known (client-visible), else from the
        # admit call; the first token is produced inside admit()
        ttft_ms = (t1 - (enqueued_ns if enqueued_ns is not None
                         else t_admit_ns)) / 1e6
        _obs.histogram("serving.ttft_ms", "ms").observe(ttft_ms)
        if _slo.check("ttft_ms", ttft_ms):
            req.slo_bad = True
        _obs.record_flow("serving.request", req.rid, "s",
                         cat="serving",
                         args={"rid": req.rid, "lane": lane})
        self._publish_occupancy()

    def _note_progress(self, req, lane, grew, t_ns):
        """`grew` tokens of `req` became host-visible at `t_ns` (one
        chunk sync): inter-token-latency samples — the chunk lands at
        once, so the gap since the request's previous host-visible
        token spreads evenly over the chunk — plus the flow step tying
        this sync into the request's chain."""
        if grew <= 0:
            return
        h = _obs.histogram("serving.itl_ms", "ms")
        gap_ms = ((t_ns - req.t_last_ns) / 1e6 / grew
                  if req.t_last_ns is not None else 0.0)
        for _ in range(grew):
            h.observe(gap_ms)
            if _slo.check("itl_ms", gap_ms):
                req.slo_bad = True
        req.t_last_ns = t_ns
        _obs.record_flow("serving.request", req.rid, "t",
                         cat="serving",
                         args={"rid": req.rid, "lane": lane,
                               "tokens": grew})

    def _note_finish(self, req, t_ns=None, evicted=False):
        """Request left the pool (finished or evicted): e2e histogram,
        goodput gauge, the flow-chain finish, a finish/evict instant,
        and the request's SLO verdict into the rolling attainment."""
        t_ns = time.perf_counter_ns() if t_ns is None else t_ns
        start = req.t_enq_ns if req.t_enq_ns is not None \
            else req.t_admit_ns
        if start is not None and not evicted:
            e2e_ms = (t_ns - start) / 1e6
            _obs.histogram("serving.e2e_ms", "ms").observe(e2e_ms)
            if _slo.check("e2e_ms", e2e_ms):
                req.slo_bad = True
        # evicted requests still delivered their synced tokens
        self._completed_tokens += req.emitted
        if self._t_serve_start_ns is not None:
            elapsed_s = (t_ns - self._t_serve_start_ns) / 1e9
            if elapsed_s > 0:
                _obs.gauge("serving.goodput_tok_s").set(
                    self._completed_tokens / elapsed_s)
        _obs.record_flow("serving.request", req.rid, "f",
                         cat="serving", args={"rid": req.rid})
        _obs.record_instant(
            "serving.evict" if evicted else "serving.finish",
            cat="serving",
            args={"rid": req.rid, "emitted": req.emitted})
        if _slo.active():
            _slo.request_complete(not req.slo_bad)

    def _note_done(self, req, reason="finish"):
        """Terminal bookkeeping every finish site runs UNCONDITIONALLY
        (unlike the _obs-gated _note_finish): releases the request's
        idempotency claim — promoting a normally-finished one into the
        dedup window so a duplicate submit re-delivers its tokens —
        and writes the journal tombstone that lets GC truncate its
        segment."""
        if req.key is not None:
            if self._idem.get(req.key) == req.rid:
                self._idem.pop(req.key, None)
            if reason == "finish":
                self._idem_done[req.key] = (req.rid, list(req.tokens))
        if self._journal is not None:
            self._journal.append_finish(
                req.rid, reason,
                tokens=req.tokens if reason == "finish" else None)

    def _publish_occupancy(self):
        """Lane and KV-cache utilization gauges — the per-replica load
        signal the ROADMAP-1 router reads off the scrape endpoint."""
        active = self.active_count
        _obs.gauge("serving.lane_occupancy").set(active)
        _obs.gauge("serving.lane_utilization").set(
            active / float(self.max_batch))
        ctx = sum(len(r.tokens) for r in self._slots if r is not None)
        _obs.gauge("serving.kv_utilization").set(
            ctx / float(self.max_batch * self.cfg.max_len))
        if self.paged:
            usable = self.num_blocks - 1
            free = self._alloc.free_blocks
            _obs.gauge("serving.kv_free_blocks").set(free)
            _obs.gauge("serving.kv_block_utilization").set(
                (usable - free) / float(usable))

    def _admit_job(self, job, enqueued_ns=None):
        """(prompt, n_new[, seed[, stop_token[, priority]]]) -> rid
        or None."""
        return self.admit(job[0], job[1],
                          seed=job[2] if len(job) > 2 else 0,
                          stop_token=job[3] if len(job) > 3 else None,
                          enqueued_ns=enqueued_ns,
                          priority=job[4] if len(job) > 4 else 0)

    def run(self, requests):
        """Convenience driver: serve `requests` (an iterable of
        (prompt, n_new[, seed[, stop_token[, priority]]])) through the
        slot pool, admitting as capacity frees. Returns {rid: tokens}
        for all of them, plus the admission order as a list of rids.
        A request preempted by a higher-priority admission is resumed
        automatically once capacity frees; its tokens land under its
        ORIGINAL rid (the resume allocates a fresh internal rid, which
        run() aliases back). With telemetry on, every job is stamped
        as enqueued at entry so queue-wait and TTFT cover time spent
        waiting for a lane. stream() does not resume preemptions —
        streaming callers own their requeue policy (the router does)."""
        enq_ns = time.perf_counter_ns() if _obs.enabled() else None
        queue = list(requests)
        order, results = [], {}
        alias = {}                     # resumed rid -> original rid
        while queue or self.preempted or self.active_count:
            while queue and self.has_capacity:
                rid = self._admit_job(queue[0], enqueued_ns=enq_ns)
                if rid is None:
                    break
                order.append(rid)
                queue.pop(0)
            # resume preempted work AFTER new admissions so a victim
            # cannot re-grab the blocks its preemptor was owed
            while self.preempted and self.has_capacity:
                req, t_ns = self.preempted[0]
                rid = self.admit_continuation(
                    req.tokens, req.n_new - req.emitted, seed=req.seed,
                    emitted=req.emitted, stop_token=req.stop_token,
                    priority=req.priority, preempted_ns=t_ns,
                    resumes=req.rid, key=req.key)
                if rid is None:
                    if not self.active_count:
                        raise RuntimeError(
                            "preempted request %d cannot resume on an "
                            "idle batcher" % req.rid)
                    break              # wait for capacity
                self.preempted.pop(0)
                alias[rid] = alias.get(req.rid, req.rid)
            results.update(self.step())
        if alias:
            results = {alias.get(rid, rid): toks
                       for rid, toks in results.items()}
        return results, order

    def stream(self, requests):
        """Streaming driver: yields ``(rid, token, done)`` the moment
        each token is produced — the first token right at admission
        (it comes from the prefill logits), then one per decode step
        per active lane; ``done`` marks a request's final token. Same
        admission policy and token streams as run() (the per-request
        generated tokens, concatenated, are identical — tested), but a
        caller can forward tokens to clients with no per-request
        buffering. A request cancel()ed between yields gets one
        terminal ``(rid, None, True)`` event — token None, since
        eviction produces no new token — so consumers keying cleanup
        off ``done`` always see it."""
        enq_ns = time.perf_counter_ns() if _obs.enabled() else None
        queue = list(requests)
        live = {}                    # rid -> Request (for delta tracking)
        while queue or self.active_count:
            while queue and self.has_capacity:
                rid = self._admit_job(queue[0], enqueued_ns=enq_ns)
                if rid is None:
                    break
                queue.pop(0)
                req = next(r for r in self._slots
                           if r is not None and r.rid == rid)
                live[rid] = req
                yield rid, req.tokens[-1], req.done
            already = {rid: req.emitted for rid, req in live.items()}
            finished = self.step()
            for rid, req in list(live.items()):
                grew = req.emitted - already[rid]   # up to chunk_size
                for off in range(grew):
                    last = off == grew - 1
                    yield (rid, req.tokens[-grew + off],
                           last and rid in finished)
                if rid in finished:
                    del live[rid]
                elif req not in self._slots:
                    # cancel()ed between yields: slot already freed, so
                    # step() will never report it finished — emit the
                    # terminal event ourselves
                    yield rid, None, True
                    del live[rid]
